"""Morton-range partition planning for the sharded cascade.

The uniform data-parallel path splits points round-robin, so every
shard produces partials for the WHOLE key space and the cross-chip
merge must re-aggregate full-pyramid partials. This module plans a
spatial split instead: P-1 detail-zoom Morton codes chosen from a
sampled quantile sketch of the input, so each mesh shard owns one
contiguous Z-order range. Because the pyramid parent is ``code >> 2``
(order-preserving), a contiguous detail range rolls up locally at every
level — the only keys two shards can both hold partials for are parent
tiles whose children straddle a split code, and there are at most
``P-1`` such tiles per level (``tilemath.split_boundary_codes_np``).
The cross-chip exchange therefore shrinks from full-pyramid partials to
that boundary set (arXiv 1509.00910, arXiv 1304.1835).

Skew resistance: after the initial quantile split the planner
iteratively re-splits the heaviest range at its sampled median and
merges the lightest adjacent pair, until no range holds more than
``balance_factor`` times the mean sampled mass (or the heavy range is a
single irreducible code). The result is deterministic for a fixed
sample seed.

A plan whose mass still concentrates in one range (``degenerate``) is
the signal for the dispatch layer to fall back to uniform DP rather
than serialize the job on one shard (``pipeline.batch._dp_mesh_for``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

import numpy as np

from heatmap_tpu import obs
from heatmap_tpu.tilemath import (
    morton_range_shards_np,
    split_boundary_codes_np,
)

#: Sampled sketch size: quantiles over 64Ki points bound the relative
#: rank error near 1/sqrt(sample) — far finer than the balance factor
#: the re-split loop enforces.
DEFAULT_SAMPLE_SIZE = 1 << 16

#: A range may hold at most this multiple of the mean sampled mass
#: before the planner re-splits it. 1.25 keeps the ISSUE's skew gate
#: (max/mean <= 2.0) with margin for sampling noise.
DEFAULT_BALANCE_FACTOR = 1.25

#: A plan is degenerate when one range holds this fraction of the
#: sampled mass after re-splitting: range sharding would serialize the
#: job on one shard, so dispatch falls back to uniform DP.
DEGENERATE_MASS = 0.9


def _range_counts(splits: np.ndarray, samp: np.ndarray) -> np.ndarray:
    """Sampled points per range under ``splits`` (sorted sample)."""
    shards = np.searchsorted(splits, samp, side="right")
    return np.bincount(shards, minlength=len(splits) + 1)


@dataclasses.dataclass(frozen=True)
class PartitionPlan:
    """A Morton-range split of the detail-zoom key space into
    ``n_shards`` contiguous ranges.

    ``splits`` are sorted detail codes; a code belongs to shard
    ``k = #{splits <= code}`` (a split opens the range to its right —
    the single ownership convention shared with the router and the
    kernel). Duplicate splits are legal and denote empty ranges.
    """

    detail_zoom: int
    n_shards: int
    splits: tuple  # (n_shards - 1,) sorted int detail codes
    sampled_points: int
    balance_factor: float
    shard_mass: tuple  # sampled mass fraction per shard
    resplits: int
    fingerprint: str

    @property
    def skew_ratio(self) -> float:
        """Max/mean sampled shard mass; 1.0 is perfectly balanced."""
        if not self.shard_mass or sum(self.shard_mass) <= 0:
            return 1.0
        mean = sum(self.shard_mass) / len(self.shard_mass)
        return max(self.shard_mass) / mean

    @property
    def degenerate(self) -> bool:
        """True when range sharding would serialize on one shard."""
        if self.n_shards < 2 or self.sampled_points == 0:
            return True
        nonempty = sum(1 for m in self.shard_mass if m > 0)
        return nonempty < 2 or max(self.shard_mass) >= DEGENERATE_MASS

    def shard_of_codes(self, codes) -> np.ndarray:
        """Owning shard index per detail code (int32)."""
        return morton_range_shards_np(np.asarray(self.splits, np.int64),
                                      codes)

    def boundary_codes(self, levels: int) -> np.ndarray:
        """Parent codes ``levels`` above detail straddling a split."""
        return split_boundary_codes_np(
            np.asarray(self.splits, np.int64), levels)

    def boundary_tiles_total(self, n_levels: int) -> int:
        """Straddling tiles summed over coarse levels 1..n_levels —
        the entire per-pyramid cross-shard merge key set."""
        return sum(len(self.boundary_codes(lvl))
                   for lvl in range(1, n_levels + 1))

    def code_ranges(self) -> list:
        """Per-shard ``[lo, hi)`` detail-code ranges covering the full
        ``[0, 4^detail_zoom)`` key space."""
        total = 1 << (2 * self.detail_zoom)
        edges = [0, *[int(s) for s in self.splits], total]
        return [(edges[k], edges[k + 1]) for k in range(self.n_shards)]


def plan_partition(codes, n_shards: int, *, detail_zoom: int, valid=None,
                   sample_size: int = DEFAULT_SAMPLE_SIZE, seed: int = 0,
                   balance_factor: float = DEFAULT_BALANCE_FACTOR,
                   max_resplits=None, n_levels=None) -> PartitionPlan:
    """Plan ``n_shards`` contiguous Morton ranges from sampled codes.

    Deterministic for fixed ``(codes, n_shards, seed)``. ``valid``
    masks lanes whose codes are garbage (out-of-projection points);
    they carry no mass. ``n_levels``, when given, sizes the
    boundary-tile count folded into the planned-event metrics.
    """
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    codes = np.asarray(codes, np.int64)
    if valid is not None:
        codes = codes[np.asarray(valid, bool)]
    rng = np.random.default_rng(seed)
    if len(codes) > sample_size:
        samp = codes[rng.choice(len(codes), size=sample_size,
                                replace=False)]
    else:
        samp = codes
    samp = np.sort(samp)
    m = len(samp)
    P = int(n_shards)

    resplits = 0
    if m == 0 or P == 1:
        # Nothing to learn from: geometric even split of the key space
        # (callers treat the zero-sample plan as degenerate anyway).
        total = 1 << (2 * detail_zoom)
        splits = np.asarray(
            [(i + 1) * total // P for i in range(P - 1)], np.int64)
    else:
        splits = samp[np.minimum(
            np.arange(1, P) * m // P, m - 1)].astype(np.int64)
        if max_resplits is None:
            max_resplits = 4 * P
        for _ in range(int(max_resplits)):
            c = _range_counts(splits, samp)
            worst = int(np.argmax(c))
            if c[worst] <= balance_factor * (m / P):
                break
            starts = np.concatenate(([0], np.cumsum(c)))
            sl = samp[starts[worst]:starts[worst + 1]]
            med = sl[len(sl) // 2]
            if med == sl[0]:
                # Median collides with the range's smallest code; the
                # first strictly-greater sample still moves mass left.
                gt = int(np.searchsorted(sl, sl[0], side="right"))
                if gt >= len(sl):
                    break  # single-code hotspot: irreducible
                med = sl[gt]
            cand = np.sort(np.append(splits, med))
            jm = int(np.searchsorted(cand, med))
            c2 = _range_counts(cand, samp)
            # Fund the new split by merging the lightest adjacent pair
            # (never the pair the new split just created).
            pair = c2[:-1] + c2[1:]
            pair[jm] = np.iinfo(pair.dtype).max if pair.dtype.kind in "iu" \
                else np.inf
            best_j = int(np.argmin(pair))
            if best_j == jm:
                break
            splits = np.delete(cand, best_j)
            resplits += 1

    mass = (_range_counts(splits, samp) / m if m else
            np.zeros(P, np.float64))
    payload = {"detail_zoom": int(detail_zoom), "n_shards": P,
               "splits": [int(s) for s in splits], "seed": int(seed),
               "balance_factor": float(balance_factor),
               "sampled_points": int(m)}
    fp = hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()
    plan = PartitionPlan(
        detail_zoom=int(detail_zoom), n_shards=P,
        splits=tuple(int(s) for s in splits), sampled_points=int(m),
        balance_factor=float(balance_factor),
        shard_mass=tuple(float(x) for x in mass), resplits=resplits,
        fingerprint=fp)
    obs.record_partition_planned(
        plan,
        boundary_tiles=(plan.boundary_tiles_total(n_levels)
                        if n_levels is not None else None))
    return plan


def split_range_median(codes, weights, lo: int, hi: int):
    """Weighted-median split code for one hot Morton range ``[lo, hi)``.

    The write plane's rebalance uses the same move the re-split loop
    above makes — cut the heavy range at its mass median — but against
    the range's *materialized* cell codes/weights (the compacted base's
    detail rows) instead of a point sample. Returns an int split code
    ``s`` with ``lo < s < hi`` such that roughly half the in-range mass
    lands in ``[lo, s)``, or ``None`` when the range is irreducible
    (empty, or all mass on its smallest code).
    """
    codes = np.asarray(codes, np.int64)
    weights = np.asarray(weights, np.float64)
    keep = (codes >= lo) & (codes < hi) & (weights > 0)
    codes, weights = codes[keep], weights[keep]
    if len(codes) == 0:
        return None
    order = np.argsort(codes, kind="stable")
    codes, weights = codes[order], weights[order]
    cum = np.cumsum(weights)
    idx = int(np.searchsorted(cum, cum[-1] / 2.0, side="left"))
    med = int(codes[min(idx, len(codes) - 1)])
    if med <= lo:
        # All of the left half sits on the smallest code; the first
        # strictly-greater code still moves mass left (same escape the
        # planner's re-split loop takes).
        gt = int(np.searchsorted(codes, med, side="right"))
        if gt >= len(codes):
            return None  # single-code hotspot: irreducible
        med = int(codes[gt])
    if not (lo < med < hi):
        return None
    return med


def route_emissions(plan: PartitionPlan, codes, slots, valid=None,
                    weights=None, bucket=None):
    """Scatter emission lanes into per-shard contiguous segments.

    Returns ``(codes, slots, valid, weights, seg_len)`` where each
    array is ``(n_shards * seg_len,)`` and shard ``k``'s lanes occupy
    ``[k*seg_len, (k+1)*seg_len)``; pad lanes are ``valid=False`` —
    the masking path every cascade kernel already drops. Invalid input
    lanes are dropped here (they carry garbage codes that would skew a
    shard's segment for no output). ``bucket`` maps the raw max shard
    count to a padded segment length so per-range shapes hit the
    bucketed compile cache.
    """
    codes = np.asarray(codes, np.int64)
    slots = np.asarray(slots)
    v_mask = (np.ones(len(codes), bool) if valid is None
              else np.asarray(valid, bool))
    w = None if weights is None else np.asarray(weights)
    P = plan.n_shards

    src = np.flatnonzero(v_mask)
    sid = plan.shard_of_codes(codes[src])
    order = np.argsort(sid, kind="stable")
    src, sid = src[order], sid[order]
    counts = np.bincount(sid, minlength=P)
    seg = max(int(counts.max()) if len(counts) else 0, 1)
    if bucket is not None:
        seg = max(int(bucket(seg)), seg)
    starts = np.concatenate(([0], np.cumsum(counts)))
    dst = sid * seg + (np.arange(len(src)) - starts[sid])

    out_codes = np.zeros(P * seg, codes.dtype)
    out_slots = np.zeros(P * seg, slots.dtype)
    out_valid = np.zeros(P * seg, bool)
    out_codes[dst] = codes[src]
    out_slots[dst] = slots[src]
    out_valid[dst] = True
    out_w = None
    if w is not None:
        out_w = np.zeros(P * seg, w.dtype)
        out_w[dst] = w[src]
    return out_codes, out_slots, out_valid, out_w, seg
