"""Multi-host execution: DCN-aware meshes, ingest sharding, egress merge.

The reference scales out by adding Spark executors behind a k8s external
shuffle service (reference submit-heatmap:9-13); its "communication
backend" is the JVM shuffle over the pod network (SURVEY.md §2.3). The
TPU-native equivalent (BASELINE.md config 5, 10B points on v5e-64):

- every host runs this same program (SPMD) after ``initialize()``
  (``jax.distributed`` — on TPU pods coordinator/process-id/count
  auto-detect from the runtime environment);
- ingest is sharded by process: each host reads only its slice of the
  source (``process_shard_bounds`` — the Cassandra-token-range analog),
  then shards its points over its local devices on the mesh's data
  axis;
- device collectives (psum / psum_scatter in parallel.sharded) ride
  ICI within a host and DCN across hosts. ``make_hybrid_mesh`` orders
  devices so consecutive data-axis neighbors are ICI-local (XLA then
  hierarchically decomposes cross-host reductions: reduce over ICI
  first, DCN once per host);
- final blob egress merges across hosts with ``gather_blobs`` (DCN
  byte-level allgather via jax.experimental.multihost_utils), the
  analog of the reference's driver-side collect before the Cassandra
  write (reference heatmap.py:156-158).

Everything degrades to a no-op on a single process, so the same job
script runs unchanged from a laptop CPU to a v5e-64 pod.
"""

from __future__ import annotations

import json

import jax
import numpy as np

from heatmap_tpu.parallel.mesh import make_mesh


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None):
    """Bring up jax.distributed (no-op if already initialized or
    single-process with no coordinator configured).

    On TPU pods all three arguments auto-detect; on CPU/GPU clusters
    pass them explicitly (the JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID env vars also work).
    """
    import os

    # Detect a prior distributed init WITHOUT touching the backend:
    # jax.process_count() would initialize the local backend, after
    # which distributed.initialize() always raises and the job would
    # silently run single-process.
    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return  # already distributed
    except (ImportError, AttributeError):
        # private API moved/renamed; fall through to initialize
        pass
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
        or any(os.environ.get(v) for v in (
            "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
        ))
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError):
        if explicit:
            # The caller configured a cluster; failing to join it is an
            # error, not a single-process fallback.
            raise
        # Single-process environment (no coordinator discoverable) — fine.
        pass


def make_hybrid_mesh(tile: int = 1, devices=None) -> jax.sharding.Mesh:
    """A (data, tile) mesh whose data-axis device order is DCN-aware.

    Multi-process: devices are ordered host-major (each host's local
    devices contiguous), so neighboring data-axis positions are
    ICI-connected and XLA lowers data-axis reductions hierarchically
    (ICI ring per host, then one DCN hop per host pair) — the layout
    "How to Scale Your Model" prescribes for DP over pods. The axis
    NAME stays ``data``, so every kernel in parallel.sharded works
    unchanged on a pod.

    Single-process: identical to ``make_mesh``.
    """
    if devices is None:
        devices = jax.devices()
    if jax.process_count() > 1:
        # jax.devices() is already process-major on TPU pods, but make
        # it explicit (and stable) rather than relying on enumeration
        # order: sort by (process_index, local id).
        devices = sorted(
            devices, key=lambda d: (d.process_index, d.id)
        )
    return make_mesh(tile=tile, devices=devices)


def process_shard_bounds(n: int, process_count: int | None = None,
                         process_index: int | None = None) -> tuple[int, int]:
    """[start, end) slice of an n-element source this process ingests.

    Balanced like Spark's even token-range split: first ``n % k``
    shards get one extra element. Deterministic, so failed-host
    re-execution re-reads exactly the same slice (SURVEY.md §5
    fault-tolerance model).
    """
    k = jax.process_count() if process_count is None else process_count
    i = jax.process_index() if process_index is None else process_index
    if not 0 <= i < k:
        raise ValueError(f"process_index {i} out of range for {k} processes")
    base, extra = divmod(n, k)
    start = i * base + min(i, extra)
    return start, start + base + (1 if i < extra else 0)


def shard_source_rows(source_batches, n_total: int, batch_size: int,
                      process_count: int | None = None,
                      process_index: int | None = None):
    """Yield only this process's batches from a deterministic source.

    ``source_batches`` must yield fixed-size batches (``batch_size``
    rows, last one ragged) in a deterministic order; batch indices are
    partitioned by ``process_shard_bounds`` over the batch count. The
    host-level analog of the per-device point sharding inside the mesh.
    """
    n_batches = -(-n_total // batch_size) if n_total else 0
    lo, hi = process_shard_bounds(n_batches, process_count, process_index)
    for i, batch in enumerate(source_batches):
        if i >= hi:
            break
        if i >= lo:
            yield batch


def gather_blobs(local_blobs: dict, max_bytes: int = 1 << 30) -> dict:
    """Merge per-process blob dicts across hosts (DCN allgather).

    Values must be JSON-serializable (the pipeline emits JSON strings
    already). Key collisions across hosts are summed when both sides
    are numeric dicts, else last-process-wins — with process-sharded
    ingest and slot-complete cascades, collisions only occur for
    result tiles whose detail tiles straddle host shards, where the
    inner dicts are disjoint-or-summable by construction.

    Single-process: returns ``local_blobs`` unchanged.
    """
    if jax.process_count() == 1:
        return local_blobs
    from jax.experimental import multihost_utils

    payload = json.dumps(local_blobs).encode()
    if len(payload) > max_bytes:
        raise ValueError(
            f"local blob payload {len(payload)}B exceeds max_bytes; "
            f"raise max_bytes or write per-host sinks instead"
        )
    # Fixed-width frame: [length:8][payload][zero pad] so allgather is
    # a dense u8 array.
    n = np.asarray([len(payload)], np.int64)
    max_len = int(multihost_utils.process_allgather(n).max())
    frame = np.zeros(max_len + 8, np.uint8)
    frame[:8] = np.frombuffer(np.int64(len(payload)).tobytes(), np.uint8)
    frame[8 : 8 + len(payload)] = np.frombuffer(payload, np.uint8)
    frames = multihost_utils.process_allgather(frame)  # (k, max_len+8)
    merged: dict = {}
    for row in np.asarray(frames):
        ln = int(np.frombuffer(row[:8].tobytes(), np.int64)[0])
        part = json.loads(row[8 : 8 + ln].tobytes().decode())
        for key, val in part.items():
            if key in merged:
                merged[key] = _merge_blob_values(merged[key], val)
            else:
                merged[key] = val
    return merged


def _merge_blob_values(a, b):
    """Sum two blob values that may be JSON strings of {tile: count}."""
    decode = isinstance(a, str)
    da = json.loads(a) if decode else a
    db = json.loads(b) if isinstance(b, str) else b
    if isinstance(da, dict) and isinstance(db, dict):
        out = dict(da)
        for k, v in db.items():
            out[k] = out.get(k, 0) + v if isinstance(v, (int, float)) else v
        return json.dumps(out) if decode else out
    return b


def shard_source(source, process_count: int | None = None,
                 process_index: int | None = None):
    """This process's view of a range-shardable source.

    Sources that expose ``shard_index``/``shard_count`` dataclass
    fields (CassandraSource token ranges, CosmosDBSource partition key
    ranges) re-instantiate with this process's interleaved assignment —
    the real connector-style input-split sharding, no row counting
    needed. Returns None for sources without native sharding (callers
    fall back to row slicing).
    """
    import dataclasses

    if not (dataclasses.is_dataclass(source)
            and hasattr(source, "shard_index")
            and hasattr(source, "shard_count")):
        return None
    k = jax.process_count() if process_count is None else process_count
    i = jax.process_index() if process_index is None else process_index
    if source.shard_count != 1:
        raise ValueError(
            "source already carries a shard assignment "
            f"(shard {source.shard_index}/{source.shard_count}); pass an "
            "unsharded source to run_job_multihost"
        )
    return dataclasses.replace(source, shard_index=i, shard_count=k)


def run_job_multihost(source, sink=None, config=None,
                      batch_size: int = 1 << 20,
                      n_total: int | None = None):
    """Process-sharded ``run_job``: each host ingests its slice of the
    source, aggregates on its local devices, and the blob dicts merge
    over DCN at the end (only process 0 writes the sink).

    Range-shardable sources (``shard_index``/``shard_count`` fields —
    Cassandra token ranges, CosmosDB partition key ranges) shard by
    range assignment via :func:`shard_source`. Otherwise ``n_total``
    (total source rows) enables exact batch-count sharding; without
    it, single-process falls through to run_job and multi-process
    raises (sources must declare their size to shard — SyntheticSource
    has ``n``; files can be pre-counted).
    """
    from heatmap_tpu.pipeline import BatchJobConfig, run_job
    from heatmap_tpu.pipeline.batch import _run_loaded, ingest_columns

    config = config or BatchJobConfig()
    if sink is not None and hasattr(sink, "write_levels"):
        # The multi-process egress merges reference-format blob dicts
        # over DCN; a columnar sink would crash at the final write.
        # Refuse at submit time instead (the single-process fallthrough
        # WOULD work, which makes the pod-only crash extra surprising).
        raise ValueError(
            "run_job_multihost egress is blob-based; columnar sinks "
            "(arrays:/LevelArraysSink) are not supported here — use a "
            "blob sink, or run per-host jobs with columnar output"
        )
    if jax.process_count() == 1:
        return run_job(source, sink, config, batch_size=batch_size)
    sharded = shard_source(source)
    if sharded is not None:
        batches = sharded.batches(batch_size)
    else:
        if n_total is None:
            n_total = getattr(source, "n", None)
            if n_total is None:
                raise ValueError(
                    "multi-host sharding needs n_total (source row count) "
                    "or a range-shardable source"
                )
        batches = shard_source_rows(source.batches(batch_size), n_total,
                                    batch_size)
    data = ingest_columns(batches, config)
    if data is not None:
        # Cross-host blob merge: gather_blobs sums colliding numeric
        # dicts, which is exactly the weighted semantics too (f64 sums
        # are linear across host shards).
        local = _run_loaded(data, config, as_json=True)
    else:
        local = {}
    blobs = gather_blobs(local)
    if sink is not None and jax.process_index() == 0:
        sink.write(blobs.items())
    return blobs
