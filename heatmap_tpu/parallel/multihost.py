"""Multi-host execution: DCN-aware meshes, ingest sharding, egress merge.

The reference scales out by adding Spark executors behind a k8s external
shuffle service (reference submit-heatmap:9-13); its "communication
backend" is the JVM shuffle over the pod network (SURVEY.md §2.3). The
TPU-native equivalent (BASELINE.md config 5, 10B points on v5e-64):

- every host runs this same program (SPMD) after ``initialize()``
  (``jax.distributed`` — on TPU pods coordinator/process-id/count
  auto-detect from the runtime environment);
- ingest is sharded by process: each host reads only its slice of the
  source (``process_shard_bounds`` — the Cassandra-token-range analog),
  then shards its points over its local devices on the mesh's data
  axis;
- device collectives (psum / psum_scatter in parallel.sharded) ride
  ICI within a host and DCN across hosts. ``make_hybrid_mesh`` orders
  devices so consecutive data-axis neighbors are ICI-local (XLA then
  hierarchically decomposes cross-host reductions: reduce over ICI
  first, DCN once per host);
- egress: ``gather_blobs`` (DCN byte-level allgather, every host gets
  the full merged dict, process 0 writes) is the default / small-job
  path — the analog of the reference's driver-side collect
  (heatmap.py:156-158). Tile-space-sharded egress is the explicit
  opt-in (``egress="sharded"``, per-host sink paths required):
  ``scatter_blobs`` partitions the blob keyspace deterministically
  over processes (``blob_owner``; ``scatter_levels`` uses the
  equivalent name+tile hash ``_level_row_owner`` for columnar rows)
  and one all-to-all moves each blob to its owner, which writes its
  own sink shard — the analog of the reference's Spark reducers each
  writing their hash partition to Cassandra (heatmap.py:149-150).

Everything degrades to a no-op on a single process, so the same job
script runs unchanged from a laptop CPU to a v5e-64 pod.
"""

from __future__ import annotations

import json
import zlib

import jax
import numpy as np

from heatmap_tpu import obs
from heatmap_tpu.obs import tracing
from heatmap_tpu.io.sinks import LevelArraysSink as _LevelArraysSink
# Merge semantics live in the jax-free io.merge module (the CLI's
# offline shard merge uses them without an accelerator stack);
# re-exported here because every distributed egress path and its
# tests address them through this module.
from heatmap_tpu.io.merge import (  # noqa: F401
    _merge_blob_values,
    merge_blob_parts,
    merge_level_parts,
)
from heatmap_tpu.parallel.mesh import make_mesh, shard_map


class StragglerTimeout(RuntimeError):
    """A host's heartbeat went stale past the configured deadline.

    Raised by :func:`check_heartbeats` so a straggling or dead host
    turns into a typed, catchable error at the next phase boundary
    instead of the job hanging in a collective forever. Carries the
    offending ``{process: age_s}`` map as ``.stale``.
    """

    def __init__(self, deadline_s: float, stale: dict):
        detail = ", ".join(f"process {p}: {age:.1f}s"
                           for p, age in sorted(stale.items()))
        super().__init__(
            f"heartbeat deadline {deadline_s}s exceeded ({detail})")
        self.deadline_s = float(deadline_s)
        self.stale = dict(stale)


def check_heartbeats(deadline_s: float, now: float | None = None,
                     expected=None) -> dict:
    """Raise :class:`StragglerTimeout` if any host's last heartbeat is
    older than ``deadline_s``; otherwise return the age map.

    Reads ``obs.heartbeat_ages()`` (the ``multihost_last_heartbeat_ts``
    gauge), so it only sees hosts whose heartbeats reach this process's
    registry — per-process in the current transport, which is exactly
    the lost-heartbeat failure mode the ``multihost.heartbeat`` fault
    site injects. A disabled registry yields no ages and never times
    out (monitoring off means no straggler detection, not a crash).

    A host that *never* heartbeats is invisible to the age map — it has
    no gauge sample to go stale. ``expected`` closes that gap: an
    iterable of process labels that MUST have beaten at least once;
    any expected label absent from the ages is reported stale with age
    ``inf`` (caught at the first phase boundary, not after a hang).
    Without ``expected`` the historical observed-hosts-only semantics
    are unchanged — see docs/robustness.md for the distinction.
    """
    if deadline_s is None or deadline_s <= 0:
        raise ValueError("deadline_s must be a positive number of seconds")
    ages = obs.heartbeat_ages(now)
    stale = {p: age for p, age in ages.items() if age > deadline_s}
    if expected is not None:
        for p in expected:
            if str(p) not in ages:
                stale[str(p)] = float("inf")
    if stale:
        raise StragglerTimeout(deadline_s, stale)
    return ages


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None):
    """Bring up jax.distributed (no-op if already initialized or
    single-process with no coordinator configured).

    On TPU pods all three arguments auto-detect; on CPU/GPU clusters
    pass them explicitly (the JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID env vars also work).
    """
    import os

    # Detect a prior distributed init WITHOUT touching the backend:
    # jax.process_count() would initialize the local backend, after
    # which distributed.initialize() always raises and the job would
    # silently run single-process.
    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return  # already distributed
    except (ImportError, AttributeError):
        # private API moved/renamed; fall through to initialize
        pass
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
        or any(os.environ.get(v) for v in (
            "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
        ))
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError):
        if explicit:
            # The caller configured a cluster; failing to join it is an
            # error, not a single-process fallback.
            raise
        # Single-process environment (no coordinator discoverable) — fine.
        pass


def make_hybrid_mesh(tile: int = 1, devices=None) -> jax.sharding.Mesh:
    """A (data, tile) mesh whose data-axis device order is DCN-aware.

    Multi-process: devices are ordered host-major (each host's local
    devices contiguous), so neighboring data-axis positions are
    ICI-connected and XLA lowers data-axis reductions hierarchically
    (ICI ring per host, then one DCN hop per host pair) — the layout
    "How to Scale Your Model" prescribes for DP over pods. The axis
    NAME stays ``data``, so every kernel in parallel.sharded works
    unchanged on a pod.

    Single-process: identical to ``make_mesh``.
    """
    if devices is None:
        devices = jax.devices()
    if jax.process_count() > 1:
        # jax.devices() is already process-major on TPU pods, but make
        # it explicit (and stable) rather than relying on enumeration
        # order: sort by (process_index, local id).
        devices = sorted(
            devices, key=lambda d: (d.process_index, d.id)
        )
    return make_mesh(tile=tile, devices=devices)


def process_shard_bounds(n: int, process_count: int | None = None,
                         process_index: int | None = None) -> tuple[int, int]:
    """[start, end) slice of an n-element source this process ingests.

    Balanced like Spark's even token-range split: first ``n % k``
    shards get one extra element. Deterministic, so failed-host
    re-execution re-reads exactly the same slice (SURVEY.md §5
    fault-tolerance model).
    """
    k = jax.process_count() if process_count is None else process_count
    i = jax.process_index() if process_index is None else process_index
    if not 0 <= i < k:
        raise ValueError(f"process_index {i} out of range for {k} processes")
    base, extra = divmod(n, k)
    start = i * base + min(i, extra)
    return start, start + base + (1 if i < extra else 0)


def shard_source_rows(source_batches, n_total: int, batch_size: int,
                      process_count: int | None = None,
                      process_index: int | None = None):
    """Yield only this process's batches from a deterministic source.

    ``source_batches`` must yield fixed-size batches (``batch_size``
    rows, last one ragged) in a deterministic order; batch indices are
    partitioned by ``process_shard_bounds`` over the batch count. The
    host-level analog of the per-device point sharding inside the mesh.
    """
    n_batches = -(-n_total // batch_size) if n_total else 0
    lo, hi = process_shard_bounds(n_batches, process_count, process_index)
    for i, batch in enumerate(source_batches):
        if i >= hi:
            break
        if i >= lo:
            yield batch


def gather_blobs(local_blobs: dict, max_bytes: int = 1 << 30) -> dict:
    """Merge per-process blob dicts across hosts (DCN allgather).

    Values must be JSON-serializable (the pipeline emits JSON strings
    already). Key collisions across hosts SUM — with process-sharded
    ingest and slot-complete cascades, collisions only occur for
    result tiles whose detail tiles straddle host shards, where the
    inner dicts are disjoint-or-summable by construction; a
    non-summable collision therefore indicates corruption and raises
    (_merge_blob_values), never resolves last-process-wins.

    Single-process: returns ``local_blobs`` unchanged.
    """
    if jax.process_count() == 1:
        return local_blobs
    from jax.experimental import multihost_utils

    payload = json.dumps(local_blobs).encode()
    if len(payload) > max_bytes:
        raise ValueError(
            f"local blob payload {len(payload)}B exceeds max_bytes; "
            f"raise max_bytes or write per-host sinks instead"
        )
    # Fixed-width frame: [length:8][payload][zero pad] so allgather is
    # a dense u8 array.
    n = np.asarray([len(payload)], np.int64)
    max_len = int(multihost_utils.process_allgather(n).max())
    frame = np.zeros(max_len + 8, np.uint8)
    frame[:8] = np.frombuffer(np.int64(len(payload)).tobytes(), np.uint8)
    frame[8 : 8 + len(payload)] = np.frombuffer(payload, np.uint8)
    frames = multihost_utils.process_allgather(frame)  # (k, max_len+8)
    merged: dict = {}
    for row in np.asarray(frames):
        ln = int(np.frombuffer(row[:8].tobytes(), np.int64)[0])
        part = json.loads(row[8 : 8 + ln].tobytes().decode())
        for key, val in part.items():
            if key in merged:
                merged[key] = _merge_blob_values(merged[key], val)
            else:
                merged[key] = val
    return merged


def blob_owner(blob_id: str, process_count: int) -> int:
    """Deterministic owner process of a blob key (tile-space sharding).

    crc32 of the blob id ("user|timespan|z_r_c"), mod process count —
    stable across hosts, runs and Python processes (unlike built-in
    ``hash``, which is salted). Every row of a blob maps to the same
    owner, so per-host egress shards are disjoint at blob granularity —
    the analog of the reference's Spark reducers each writing their own
    hash partition of tile space (reference heatmap.py:149-150).
    """
    return zlib.crc32(blob_id.encode()) % process_count


def partition_blobs(local_blobs: dict, process_count: int) -> list[dict]:
    """Split a local blob dict into per-owner sub-dicts (see blob_owner)."""
    parts: list[dict] = [{} for _ in range(process_count)]
    for key, val in local_blobs.items():
        parts[blob_owner(key, process_count)][key] = val
    return parts


#: Per-collective buffer bound for the byte exchange: a shift round
#: wider than this splits into chunked ppermutes, so device frames
#: never exceed it no matter how large a single payload is.
_EXCHANGE_CHUNK_BYTES = 1 << 28


def _process_mesh():
    """1 device per process, process-ordered, so mesh position ==
    process index and shift arithmetic addresses real processes."""
    firsts: dict[int, object] = {}
    for dev in sorted(jax.devices(), key=lambda d: (d.process_index, d.id)):
        firsts.setdefault(dev.process_index, dev)
    return jax.sharding.Mesh(np.asarray(list(firsts.values())), ("p",))


def _alltoall_bytes(dest_payloads: list[bytes],
                    process_count: int | None = None,
                    transport=None,
                    max_bytes: int = 1 << 30,
                    chunk_bytes: int = _EXCHANGE_CHUNK_BYTES) -> list[bytes]:
    """All-to-all byte exchange: ``dest_payloads[d]`` goes to process
    d; returns the k payloads this process received (index = source).

    The sharded-egress transport: unlike gather_blobs' allgather, each
    pair moves only its own payload, so no host ever receives (or
    holds) the full blob set. Single-process: identity. ``transport``
    (tests, alternative backends) overrides the default implementation:
    a callable ``(dest_payloads) -> received_payloads``.

    Default multi-process transport rides the same device fabric as
    the compute collectives (DCN across hosts — "How to Scale Your
    Model"'s host-transfer recipe, not a sidecar TCP mesh), SKEW-PROOF
    by construction (VERDICT r3 weak #5 — the earlier dense
    (k, global-max) frame let one hot pair pad every row):

    1. one small allgather publishes the k×k length matrix, so every
       process knows every pair's exact payload size;
    2. the exchange decomposes into k-1 ``lax.ppermute`` shift rounds
       (round s: p -> p+s mod k). Each round's buffer is sized by THAT
       shift class's maximum only, so a single 500 MB pair inflates
       its own round, not the other k-2;
    3. a round wider than ``chunk_bytes`` splits into chunked
       ppermutes — per-collective device memory is bounded regardless
       of payload size.

    ``max_bytes`` now guards what this process actually has to HOLD
    (the sum of payloads addressed to it — unavoidable memory for its
    owned shard) rather than a padding artifact; hitting it means the
    keyspace itself is skewed (rebalance partitioning or raise the
    cap), not that the transport framed badly.
    """
    k = jax.process_count() if process_count is None else process_count
    if len(dest_payloads) != k:
        raise ValueError(f"expected {k} payloads, got {len(dest_payloads)}")
    if transport is not None:
        return list(transport(dest_payloads))
    if k == 1:
        return [dest_payloads[0]]
    from jax.experimental import multihost_utils
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    me = jax.process_index()
    lens = np.asarray([len(p) for p in dest_payloads], np.int64)
    # L[p, d] = bytes process p sends to process d.
    L = np.asarray(multihost_utils.process_allgather(lens))
    owned = int(L[:, me].sum())
    if owned > max_bytes:
        raise ValueError(
            f"process {me} would receive {owned}B of payloads "
            f"(> max_bytes {max_bytes}); its owned shard is this large "
            "regardless of transport — rebalance the key partition or "
            "raise max_bytes"
        )
    mesh = _process_mesh()
    spec = NamedSharding(mesh, P("p"))

    received: list = [b""] * k
    received[me] = dest_payloads[me]
    for s in range(1, k):
        dst = (me + s) % k
        src = (me - s) % k
        width = int(max(L[p, (p + s) % k] for p in range(k)))
        if width == 0:
            continue
        perm = [(p, (p + s) % k) for p in range(k)]

        def body(b, perm=perm):
            return lax.ppermute(b, "p", perm)

        fn = jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("p"), out_specs=P("p")
        ))
        chunks = []
        payload = dest_payloads[dst]
        need = int(L[src, me])
        for off in range(0, width, chunk_bytes):
            w = min(chunk_bytes, width - off)
            buf = np.zeros(w, np.uint8)
            part = payload[off:off + w]
            if part:
                buf[:len(part)] = np.frombuffer(part, np.uint8)
            garr = jax.make_array_from_process_local_data(spec, buf[None])
            out = fn(garr)
            # Keep only the bytes THIS process's incoming payload
            # actually occupies — a bystander in a hot pair's round
            # must not accumulate the round's full padded width on the
            # host (it participates in the collective, then drops the
            # padding chunk by chunk).
            keep = max(0, min(w, need - off))
            if keep:
                chunks.append(np.asarray(
                    list(out.addressable_shards)[0].data
                )[0][:keep])
        received[src] = b"".join(c.tobytes() for c in chunks)
    return received


def scatter_blobs(local_blobs: dict,
                  process_count: int | None = None,
                  transport=None,
                  max_bytes: int = 1 << 30) -> dict:
    """Tile-space-sharded egress merge: each process ends with the
    fully-merged blobs it OWNS (blob_owner partition) — and nothing
    else. The scalable replacement for gather_blobs: total bytes moved
    equal the blob volume once, and per-host memory is the owned shard,
    not the whole result (VERDICT r2 missing #3; reference analog:
    distributed reducer writes, heatmap.py:149-150).

    Single-process: returns ``local_blobs`` unchanged.
    """
    k = jax.process_count() if process_count is None else process_count
    if k == 1 and transport is None:
        return local_blobs
    parts = partition_blobs(local_blobs, k)
    payloads = [json.dumps(p).encode() for p in parts]
    received = _alltoall_bytes(payloads, process_count=k,
                               transport=transport, max_bytes=max_bytes)
    return merge_blob_parts(json.loads(r.decode()) for r in received)


def _level_row_owner(lvl, process_count: int) -> np.ndarray:
    """Owner process per aggregate row of a finalized level.

    Depends only on cross-host-consistent values (user/timespan NAMES
    — per-host vocab indices differ host to host — plus the coarse
    tile and zoom), so every host routes rows of the same blob to the
    same owner. Vectorized: crc32 only over the small name tables.
    """
    mix = np.uint64(0x9E3779B97F4A7C15)
    uh = np.asarray([zlib.crc32(str(s).encode()) for s in lvl["user_names"]],
                    np.uint64)
    th = np.asarray(
        [zlib.crc32(str(s).encode()) for s in lvl["timespan_names"]],
        np.uint64,
    )
    h = uh[np.asarray(lvl["user_idx"])] * mix
    h ^= th[np.asarray(lvl["timespan_idx"])]
    h *= mix
    h ^= (np.asarray(lvl["coarse_row"], np.uint64) << np.uint64(24)) \
        ^ np.asarray(lvl["coarse_col"], np.uint64) \
        ^ (np.uint64(int(lvl["coarse_zoom"])) << np.uint64(48))
    h *= mix
    return (h % np.uint64(process_count)).astype(np.int64)


# The per-row level schema IS the columnar sink schema — one source of
# truth, so a column added there can't silently drop from the exchange.
_LEVEL_ROW_COLS = _LevelArraysSink.COLUMNS


def partition_levels(levels, process_count: int) -> list[list[dict]]:
    """Split finalized level arrays into per-owner row subsets.

    Returns ``parts[d]`` = the levels list destined to process d (same
    level schema, rows selected; name tables ride along whole — they
    are O(unique users), tiny next to the rows).
    """
    parts: list[list[dict]] = [[] for _ in range(process_count)]
    for lvl in levels:
        owner = _level_row_owner(lvl, process_count)
        for d in range(process_count):
            sel = np.flatnonzero(owner == d)
            sub = {k: np.asarray(lvl[k])[sel] for k in _LEVEL_ROW_COLS}
            sub["zoom"] = int(lvl["zoom"])
            sub["coarse_zoom"] = int(lvl["coarse_zoom"])
            sub["user_names"] = np.asarray(lvl["user_names"])
            sub["timespan_names"] = np.asarray(lvl["timespan_names"])
            parts[d].append(sub)
    return parts


def _levels_to_bytes(levels) -> bytes:
    import io as _io

    arrays = {"n_levels": np.asarray(len(levels))}
    for j, lvl in enumerate(levels):
        for key in _LEVEL_ROW_COLS + ("user_names", "timespan_names"):
            arrays[f"l{j}_{key}"] = np.asarray(lvl[key])
        arrays[f"l{j}_zoom"] = np.asarray(lvl["zoom"])
        arrays[f"l{j}_coarse_zoom"] = np.asarray(lvl["coarse_zoom"])
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _levels_from_bytes(payload: bytes) -> list[dict]:
    import io as _io

    with np.load(_io.BytesIO(payload), allow_pickle=False) as z:
        n = int(z["n_levels"])
        out = []
        for j in range(n):
            lvl = {
                key: z[f"l{j}_{key}"]
                for key in _LEVEL_ROW_COLS + ("user_names", "timespan_names")
            }
            lvl["zoom"] = int(z[f"l{j}_zoom"])
            lvl["coarse_zoom"] = int(z[f"l{j}_coarse_zoom"])
            out.append(lvl)
    return out


def scatter_levels(levels,
                   process_count: int | None = None,
                   transport=None,
                   max_bytes: int = 1 << 30) -> list[dict]:
    """Columnar analog of scatter_blobs: exchange finalized level rows
    so each process owns complete, merged rows for its blob-key shard —
    the egress that lets every host write its own LevelArraysSink
    (per-host .npz/.parquet shards of one logical columnar result).

    Single-process: returns ``levels`` unchanged.
    """
    k = jax.process_count() if process_count is None else process_count
    if k == 1 and transport is None:
        return list(levels)
    parts = partition_levels(levels, k)
    payloads = [_levels_to_bytes(p) for p in parts]
    received = _alltoall_bytes(payloads, process_count=k,
                               transport=transport, max_bytes=max_bytes)
    return merge_level_parts(_levels_from_bytes(r) for r in received)


def shard_source(source, process_count: int | None = None,
                 process_index: int | None = None):
    """This process's view of a range-shardable source.

    Sources that expose ``shard_index``/``shard_count`` dataclass
    fields (CassandraSource token ranges, CosmosDBSource partition key
    ranges) re-instantiate with this process's interleaved assignment —
    the real connector-style input-split sharding, no row counting
    needed. Returns None for sources without native sharding (callers
    fall back to row slicing).
    """
    import dataclasses

    if not (dataclasses.is_dataclass(source)
            and hasattr(source, "shard_index")
            and hasattr(source, "shard_count")):
        return None
    k = jax.process_count() if process_count is None else process_count
    i = jax.process_index() if process_index is None else process_index
    if source.shard_count != 1:
        raise ValueError(
            "source already carries a shard assignment "
            f"(shard {source.shard_index}/{source.shard_count}); pass an "
            "unsharded source to run_job_multihost"
        )
    return dataclasses.replace(source, shard_index=i, shard_count=k)


class _CaptureLevels:
    """In-memory ``write_levels`` sink: captures finalized level arrays
    so the multihost columnar path can scatter them before the real
    sink write. Accumulates across calls — the bounded path's spill
    egress finalizes one level per call, the single-shot path all
    levels in one."""

    def __init__(self):
        self.levels: list[dict] = []

    def write_levels(self, levels) -> int:
        new = list(levels)
        self.levels.extend(new)
        return sum(len(lvl["value"]) for lvl in new)


class _SliceSource:
    """This process's row-sharded slice as a re-cuttable source.

    The batch-index shard assignment is pinned at the CONSTRUCTION
    batch size: every process must cut the source at the same
    granularity or the partition drops/duplicates rows, and the
    bounded path re-reads batches at ``min(batch_size, max_points)``
    — a per-host value when the chunk size was auto-derived from that
    host's RAM. So ``batches(bs)`` always shards at the pinned size
    and re-cuts oversized batches afterwards, host-locally.
    """

    def __init__(self, source, n_total: int, batch_size: int):
        self.source = source
        self.n_total = n_total
        self.batch_size = batch_size

    def batches(self, bs: int):
        sliced = shard_source_rows(
            self.source.batches(self.batch_size), self.n_total,
            self.batch_size,
        )
        if bs >= self.batch_size:
            yield from sliced
            return
        for batch in sliced:
            n = len(batch["latitude"])
            if n <= bs:
                yield batch
                continue
            for i in range(0, n, bs):
                yield {k: v[i:i + bs] for k, v in batch.items()}


def run_job_multihost(source, sink=None, config=None,
                      batch_size: int = 1 << 20,
                      n_total: int | None = None,
                      egress: str = "auto",
                      max_points_in_flight: int | None = None,
                      egress_max_bytes: int = 1 << 30,
                      merge_spill_dir: str | None = None,
                      heartbeat_deadline_s: float | None = None,
                      on_straggler: str = "raise",
                      elastic_dir: str | None = None,
                      elastic_hosts: int | None = None,
                      elastic_opts: dict | None = None):
    """Process-sharded ``run_job``: each host ingests its slice of the
    source and aggregates on its local devices; egress then either

    - ``"sharded"`` (tile-space-sharded, the scalable path): blob keys
      partition deterministically across processes (blob_owner); an
      all-to-all moves each blob to its owner once, and EVERY process
      writes its owned shard to its own ``sink`` — the analog of the
      reference's distributed reducer writes (heatmap.py:149-150). No
      step materializes all blobs on one host. Returns this process's
      owned shard. Columnar sinks (``write_levels``) are supported:
      level rows scatter by blob key (scatter_levels) and each host
      writes per-host .npz/.parquet shards — point per-host sinks at
      distinct paths on shared storage.
    - ``"gather"``: the small-job path — gather_blobs allgathers and
      merges everything on every host; only process 0 writes. Returns
      the full blob dict everywhere. Refuses columnar sinks.
    - ``"auto"`` (default): "gather" — sharded egress means every
      process writes through ITS OWN sink, so it must be an explicit
      choice made with per-host sink paths (a shared path would have k
      hosts clobbering each other's files); auto never silently flips
      an existing gather caller into that contract. Columnar sinks on
      multiple processes therefore raise under auto, with guidance.

    Range-shardable sources (``shard_index``/``shard_count`` fields —
    Cassandra token ranges, CosmosDB partition key ranges) shard by
    range assignment via :func:`shard_source`. Otherwise ``n_total``
    (total source rows) enables exact batch-count sharding; without
    it, single-process falls through to run_job and multi-process
    raises (sources must declare their size to shard — SyntheticSource
    has ``n``; files can be pre-counted).

    ``max_points_in_flight`` composes with multi-process runs: each
    process streams ITS SLICE through the chunked cascade + host merge
    (run_job's bounded path, auto-spill included), so per-host memory
    is O(chunk + unique output keys) instead of the whole slice in one
    shot — BASELINE config 5's per-host memory story (the Spark
    analog: executors stream partitions and spill,
    submit-heatmap:14). ``None`` auto-routes exactly like run_job,
    with the fit decision made about the 1/k slice; ``0`` forces the
    single-shot slice ingest. ``merge_spill_dir`` passes through to
    the bounded path's disk-spill cross-chunk merge (run_job's knob;
    requires a positive/auto bound, same refusal rule).
    ``egress_max_bytes`` caps the egress collective's memory
    (gather_blobs' payload / the bytes a process must hold of the
    sharded exchange — the transport itself is skew-proof, see
    _alltoall_bytes) so a pathologically skewed keyspace fails loudly
    instead of OOMing a host — raise it here when a big job
    legitimately needs more.

    ``heartbeat_deadline_s`` arms straggler detection: after each phase
    boundary heartbeat, :func:`check_heartbeats` raises a typed
    :class:`StragglerTimeout` if any observed host's heartbeat is older
    than the deadline — the bounded-wait alternative to hanging in the
    next collective (docs/robustness.md). ``None`` (default) keeps the
    historical hang-and-hope behavior.

    ``on_straggler`` decides what a straggler timeout means:
    ``"raise"`` (default, today's semantics) surfaces the typed error
    and the job dies; ``"reassign"`` routes the whole job through the
    elastic execution layer (parallel/elastic.py — shard-lineage
    manifest under ``elastic_dir``, orphaned shards of a stale host
    re-executed on survivors, byte-identical output). Reassign mode
    requires ``elastic_dir`` and a columnar (``write_levels``) sink or
    no sink; ``elastic_hosts`` sets the simulated host count on a
    single process (default 2), and ``elastic_opts`` forwards advanced
    knobs (speculation, chaos wedge hooks) to
    :func:`heatmap_tpu.parallel.elastic.run_job_elastic`.
    """
    from heatmap_tpu.pipeline import BatchJobConfig, run_job
    from heatmap_tpu.pipeline.batch import (
        _auto_points_in_flight, _run_job_bounded, _run_loaded,
        ingest_columns,
    )

    config = config or BatchJobConfig()
    if on_straggler not in ("raise", "reassign"):
        raise ValueError(f"unknown on_straggler mode {on_straggler!r}")
    if on_straggler == "reassign":
        if elastic_dir is None:
            raise ValueError(
                "on_straggler='reassign' needs elastic_dir: the shard-"
                "lineage manifest is what makes failover re-execution "
                "exactly-once (parallel/elastic.py)")
        from heatmap_tpu.parallel.elastic import run_job_elastic

        return run_job_elastic(
            source, sink, config, batch_size=batch_size, n_total=n_total,
            lineage_dir=elastic_dir, n_hosts=elastic_hosts,
            heartbeat_deadline_s=heartbeat_deadline_s,
            **(elastic_opts or {}))
    if elastic_dir is not None or elastic_hosts is not None \
            or elastic_opts is not None:
        raise ValueError(
            "elastic_dir/elastic_hosts/elastic_opts only apply with "
            "on_straggler='reassign'")
    if egress not in ("auto", "gather", "sharded"):
        raise ValueError(f"unknown egress mode {egress!r}")
    columnar = sink is not None and hasattr(sink, "write_levels")
    if columnar and egress != "sharded":
        # The gather egress merges reference-format blob dicts on one
        # host; a columnar sink would crash at the final write. Refuse
        # at submit time — and never auto-pick sharded for it, because
        # sharded egress writes through every process's sink and needs
        # deliberately per-host paths.
        if jax.process_count() > 1 or egress == "gather":
            raise ValueError(
                "gather egress is blob-based; columnar sinks "
                "(arrays:/LevelArraysSink) need egress='sharded' with "
                "per-host sink paths (each process writes its own "
                "level-array shard)"
            )
    if egress == "auto":
        egress = "gather"
    if jax.process_count() == 1:
        return run_job(source, sink, config, batch_size=batch_size,
                       max_points_in_flight=max_points_in_flight,
                       merge_spill_dir=merge_spill_dir)
    sharded = shard_source(source)
    if sharded is not None:
        slice_source = sharded
    else:
        if n_total is None:
            n_total = getattr(source, "n", None)
            if n_total is None:
                raise ValueError(
                    "multi-host sharding needs n_total (source row count) "
                    "or a range-shardable source"
                )
        slice_source = _SliceSource(source, n_total, batch_size)
    if max_points_in_flight is None:
        max_points_in_flight = _auto_points_in_flight(
            source, shard_count=jax.process_count()
        )
    if merge_spill_dir is not None and not max_points_in_flight:
        raise ValueError(
            "merge_spill_dir lives on the bounded path; pass "
            "max_points_in_flight > 0 to chunk the per-process slice "
            "(run_job's refusal rule — silently ignoring the spill "
            "request would run the in-RAM merge it exists to avoid)"
        )
    # Ingest this process's slice into either captured level arrays
    # (columnar sinks) or local blobs; the egress tail below is shared
    # by both ingest routes. Each phase boundary heartbeats (per-host
    # liveness + uptime gauges, obs.heartbeat): the spread of the
    # multihost_phase_uptime_seconds gauge across processes at one
    # phase IS the straggler gap.
    def _phase(name: str):
        obs.heartbeat(name)
        if heartbeat_deadline_s is not None:
            check_heartbeats(heartbeat_deadline_s)

    _phase("ingest_start")
    cap = _CaptureLevels() if columnar else None
    # Phase regions are spans (heartbeats at their edges carry the
    # ambient traceparent, so a collector on another host can stitch
    # the per-host trees of one job together by trace_id).
    with tracing.span("multihost.ingest",
                      process=int(jax.process_index())):
        if max_points_in_flight:
            # Bounded slice ingest: chunked cascade + host-side merge
            # (auto-spill / explicit spill included) — blobs equal the
            # single-shot slice run by the same linearity the bounded
            # path already guarantees.
            local = _run_job_bounded(slice_source, cap, config,
                                     batch_size, max_points_in_flight,
                                     spill_dir=merge_spill_dir)
        else:
            data = ingest_columns(slice_source.batches(batch_size),
                                  config)
            if data is not None:
                # Cross-host blob merge sums colliding numeric dicts,
                # which is exactly the weighted semantics too (f64 sums
                # are linear across host shards).
                local = _run_loaded(data, config, as_json=True, sink=cap)
            else:
                local = {}
    _phase("ingest_done")
    with tracing.span("multihost.egress",
                      egress="levels-sharded" if columnar else egress):
        if columnar:
            owned = scatter_levels(cap.levels, max_bytes=egress_max_bytes)
            rows = sink.write_levels(owned)
            _phase("egress_done")
            return {"egress": "levels-sharded", "levels": len(owned),
                    "rows": rows}
        if egress == "sharded":
            owned = scatter_blobs(local, max_bytes=egress_max_bytes)
            if sink is not None:
                sink.write(owned.items())
            _phase("egress_done")
            return owned
        blobs = gather_blobs(local, max_bytes=egress_max_bytes)
        if sink is not None and jax.process_index() == 0:
            sink.write(blobs.items())
        _phase("egress_done")
        return blobs
