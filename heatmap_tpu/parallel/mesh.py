"""Mesh construction and host-side sharding helpers."""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names. ``data`` shards points (the RDD-partition analog);
# ``tile`` shards raster/tile space (the reducer-partition analog,
# SURVEY.md §2.3 "spatial parallelism").
DATA_AXIS = "data"
TILE_AXIS = "tile"


def shard_map(body, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    jax promoted shard_map out of jax.experimental and renamed its
    replication-check knob (``check_rep`` -> ``check_vma``) along the
    way; every mesh kernel in this package routes through this shim so
    the kernels run on both sides of that line unchanged.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def named_sharding(mesh, *spec):
    """``jax.sharding.NamedSharding(mesh, PartitionSpec(*spec))`` across
    jax versions.

    The GSPMD cascade (parallel/gspmd.py) annotates global-view arrays
    with NamedSharding instead of entering shard_map; this shim is its
    version seam, mirroring :func:`shard_map` above. jax < 0.4.20 spelt
    the class ``MeshPspecSharding`` — fall back to it so the gspmd entry
    points import (and run) on the same jax range the shard_map kernels
    support.
    """
    from jax.sharding import PartitionSpec

    cls = getattr(jax.sharding, "NamedSharding", None)
    if cls is None:  # pragma: no cover - ancient jax only
        cls = jax.sharding.MeshPspecSharding
    return cls(mesh, PartitionSpec(*spec))


def force_cpu_devices(n_devices: int) -> None:
    """Pin the process to an ``n_devices``-wide virtual CPU backend.

    The canonical multi-chip-dry-run shim, now shared by every entry
    point instead of living only next to the shard_map driver: newer
    jax honors ``jax_num_cpu_devices``; jax < 0.5 lacks that config
    knob (AttributeError), where the pre-init ``XLA_FLAGS``
    host-platform device count set here is what the re-init after
    ``clear_backends`` reads instead. Must run before (or while
    clearing) backend initialization — XLA_FLAGS set post-start are
    not re-read.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax.extend.backend as _jb

    _jb.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        # jax < 0.5: no jax_num_cpu_devices; XLA_FLAGS above covers it.
        pass


def make_mesh(data: int | None = None, tile: int = 1, devices=None) -> Mesh:
    """Build a (data, tile) mesh over ``devices``.

    ``data=None`` uses all remaining devices on the data axis. On a
    multi-host platform, pass ``jax.devices()`` after
    ``jax.distributed.initialize()`` and the same code spans DCN.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if data is None:
        if n % tile:
            raise ValueError(f"{n} devices not divisible by tile={tile}")
        data = n // tile
    if data * tile > n:
        raise ValueError(f"mesh {data}x{tile} needs {data * tile} devices, have {n}")
    grid = np.asarray(devices[: data * tile]).reshape(data, tile)
    return Mesh(grid, (DATA_AXIS, TILE_AXIS))


def pad_to_multiple(arrays, multiple: int, valid=None):
    """Pad 1-D point arrays to a length multiple with an explicit mask.

    shard_map needs the sharded dimension divisible by the mesh axis
    size; the pad lanes are marked invalid so kernels drop them (the
    same masking path used for out-of-range points).

    Returns (padded_arrays_list, valid_mask).
    """
    n = arrays[0].shape[0]
    for a in arrays:
        if a.shape[0] != n:
            raise ValueError("point arrays must share their leading dimension")
    pad = (-n) % multiple
    mask = np.ones(n, bool) if valid is None else np.asarray(valid, bool).copy()
    if pad == 0:
        return list(arrays), mask
    padded = [np.concatenate([np.asarray(a), np.zeros((pad,), a.dtype)]) for a in arrays]
    mask = np.concatenate([mask, np.zeros(pad, bool)])
    return padded, mask
