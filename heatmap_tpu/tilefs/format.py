"""tilefs on-disk format: mmap-ready columnar per-zoom tile files.

One ``tilefs-z{zoom:02d}.bin`` per detail zoom, laid out for zero-copy
serving: every (user, timespan) pair's Morton codes (int64) and values
(float64) are stored as contiguous 64-byte-aligned column segments,
already in the exact order :class:`heatmap_tpu.serve.store.Level` would
hold them (stable argsort by code, duplicates preserved), so the reader
hands ``np.frombuffer`` views straight to the serve tier and a tile
render touches only the handful of pages its Morton range lives on —
N backends on one host share the kernel page cache instead of keeping
N heap copies of the pyramid.

Layout::

    [header 64B]  magic TILEFS1\\n | version | endian marker | zoom |
                  coarse_zoom | crc32(header)
    [segments]    per pair: codes int64[n], values float64[n],
                  each 64-byte aligned
    [footer]      JSON index: schema, zoom, coarse_zoom, pairs
                  [{user, timespan, n, codes_off, values_off, vmax,
                    codes_crc, values_crc}]
    [trailer 24B] footer_off u64 | footer_len u32 | crc32(footer) |
                  magic TILEFSIX

The trailer magic doubles as the store-sniffing hook (a truncated
write loses it, so a torn file is detected at open, not at page-fault
time); the per-segment crcs are only checked by :func:`verify_tilefs`
(the recovery sweep) so a healthy open stays lazy — no data pages are
touched until a tile actually needs them. Integer fields are written in
native byte order with an explicit marker; a reader on the other
endianness refuses the file rather than serving garbled codes.

Writes go through the repo-wide atomic discipline: stage to ``.tmp``,
``os.replace``, under the ``sink.write`` fault site. Numpy-only on
purpose (the serve-path contract): no jax import anywhere in this
package.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import zlib

import numpy as np

from heatmap_tpu import faults

SCHEMA = "heatmap-tpu.tilefs.v1"
VERSION = 1
MAGIC = b"TILEFS1\n"
TRAILER_MAGIC = b"TILEFSIX"
#: Native-order sentinel; reads back permuted under the other
#: endianness, which is exactly the refusal signal we want.
ENDIAN_MARK = 0x01020304
HEADER_SIZE = 64
#: header fields before the crc (crc covers these bytes verbatim).
_HEADER_FMT = "=8sIIII"
_TRAILER_FMT = "=QII8s"
TRAILER_SIZE = struct.calcsize(_TRAILER_FMT)
_ALIGN = 64


class TilefsError(ValueError):
    """A tilefs file that must not be served: torn, truncated, wrong
    version, or wrong endianness. The store layer treats it as "fall
    back to the heap npz for this zoom"; the recovery sweep treats it
    as "quarantine"."""


def tilefs_path(dirpath: str, zoom: int) -> str:
    return os.path.join(dirpath, f"tilefs-z{int(zoom):02d}.bin")


def list_tilefs(dirpath: str) -> dict[int, str]:
    """{zoom: path} for every ``tilefs-z*.bin`` in ``dirpath``."""
    out: dict[int, str] = {}
    try:
        names = os.listdir(dirpath)
    except OSError:
        return out
    for name in sorted(names):
        if name.startswith("tilefs-z") and name.endswith(".bin"):
            try:
                zoom = int(name[len("tilefs-z"):-len(".bin")])
            except ValueError:
                continue
            out[zoom] = os.path.join(dirpath, name)
    return out


def sniff_tilefs(dirpath: str) -> bool:
    """True when ``dirpath`` holds at least one tilefs file with an
    intact trailer magic — the bare-path store-spec sniff (cheap: one
    stat + one 8-byte read per candidate, no footer parse)."""
    for path in list_tilefs(dirpath).values():
        try:
            with open(path, "rb") as f:
                size = os.fstat(f.fileno()).st_size
                if size < HEADER_SIZE + TRAILER_SIZE:
                    continue
                f.seek(size - 8)
                if f.read(8) == TRAILER_MAGIC:
                    return True
        except OSError:
            continue
    return False


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def write_tilefs(dirpath: str, zoom: int, coarse_zoom: int,
                 pairs) -> str:
    """Write one zoom's tilefs file; returns the final path.

    ``pairs`` is an iterable of ``(user, timespan, codes, values)``
    with codes int64 and values float64 in the caller's row order; the
    writer applies the same stable argsort-by-code that ``Level`` does,
    so the mmap reader's views are bit-identical to the heap index
    (duplicates keep their relative order, vmax is stamped in the
    footer so serving never touches a data page to learn it).
    """
    os.makedirs(dirpath, exist_ok=True)
    final = tilefs_path(dirpath, zoom)
    tmp = final + ".tmp"
    segments = []
    for user, timespan, codes, values in pairs:
        codes = np.ascontiguousarray(codes, np.int64)
        values = np.ascontiguousarray(values, np.float64)
        order = np.argsort(codes, kind="stable")
        segments.append((str(user), str(timespan),
                         codes[order], values[order]))

    def _publish():
        index = []
        with open(tmp, "wb") as f:
            f.write(b"\0" * HEADER_SIZE)  # placeholder; rewritten below
            off = HEADER_SIZE
            for user, timespan, codes, values in segments:
                codes_off = _pad(off)
                f.write(b"\0" * (codes_off - off))
                buf = codes.tobytes()
                f.write(buf)
                codes_crc = zlib.crc32(buf)
                off = codes_off + len(buf)
                values_off = _pad(off)
                f.write(b"\0" * (values_off - off))
                buf = values.tobytes()
                f.write(buf)
                off = values_off + len(buf)
                index.append({
                    "user": user, "timespan": timespan,
                    "n": int(len(codes)),
                    "codes_off": codes_off, "values_off": values_off,
                    "vmax": float(values.max()) if len(values) else 0.0,
                    "codes_crc": codes_crc,
                    "values_crc": zlib.crc32(buf),
                })
            footer = json.dumps({
                "schema": SCHEMA, "zoom": int(zoom),
                "coarse_zoom": int(coarse_zoom), "pairs": index,
            }, sort_keys=True).encode()
            footer_off = off
            f.write(footer)
            f.write(struct.pack(_TRAILER_FMT, footer_off, len(footer),
                                zlib.crc32(footer), TRAILER_MAGIC))
            head = struct.pack(_HEADER_FMT, MAGIC, VERSION, ENDIAN_MARK,
                               int(zoom), int(coarse_zoom))
            head += struct.pack("=I", zlib.crc32(head))
            f.seek(0)
            f.write(head.ljust(HEADER_SIZE, b"\0"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)

    faults.retry_call(_publish, site="sink.write", key="tilefs")
    return final


class TilefsReader:
    """One open, validated tilefs file: mmap + zero-copy column views.

    Construction checks everything that is cheap (magic, version,
    endianness, header/footer crcs, segment bounds) and nothing that is
    not (payload crcs — that is :func:`verify_tilefs`'s job), so an
    open faults in no data pages. The mmap stays alive as long as any
    returned view does (``np.frombuffer`` holds the buffer).
    """

    def __init__(self, path: str):
        self.path = path
        faults.check("tilefs.read", key=os.path.basename(path))
        with open(path, "rb") as f:
            size = os.fstat(f.fileno()).st_size
            if size < HEADER_SIZE + TRAILER_SIZE:
                raise TilefsError(f"{path}: truncated ({size} bytes)")
            self._mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        head = self._mm[:struct.calcsize(_HEADER_FMT)]
        magic, version, endian, zoom, coarse = struct.unpack(
            _HEADER_FMT, head)
        if magic != MAGIC:
            raise TilefsError(f"{path}: bad magic {magic!r}")
        if endian != ENDIAN_MARK:
            raise TilefsError(
                f"{path}: endianness mismatch (marker 0x{endian:08x}); "
                "written on a host with the other byte order")
        if version != VERSION:
            raise TilefsError(
                f"{path}: format version {version} (reader speaks "
                f"{VERSION} only)")
        (crc,) = struct.unpack_from("=I", self._mm,
                                    struct.calcsize(_HEADER_FMT))
        if crc != zlib.crc32(head):
            raise TilefsError(f"{path}: header crc mismatch")
        foot_off, foot_len, foot_crc, tmagic = struct.unpack_from(
            _TRAILER_FMT, self._mm, size - TRAILER_SIZE)
        if tmagic != TRAILER_MAGIC:
            raise TilefsError(f"{path}: trailer magic missing (torn "
                              "or truncated write)")
        if foot_off + foot_len > size - TRAILER_SIZE:
            raise TilefsError(f"{path}: footer out of bounds")
        footer = bytes(self._mm[foot_off:foot_off + foot_len])
        if zlib.crc32(footer) != foot_crc:
            raise TilefsError(f"{path}: footer crc mismatch")
        doc = json.loads(footer)
        if doc.get("schema") != SCHEMA:
            raise TilefsError(f"{path}: schema {doc.get('schema')!r}")
        if int(doc["zoom"]) != zoom or int(doc["coarse_zoom"]) != coarse:
            raise TilefsError(f"{path}: header/footer zoom disagree")
        self.zoom = zoom
        self.coarse_zoom = coarse
        self.pairs = doc["pairs"]
        for seg in self.pairs:
            n = int(seg["n"])
            end = max(int(seg["codes_off"]) + 8 * n,
                      int(seg["values_off"]) + 8 * n)
            if end > foot_off:
                raise TilefsError(
                    f"{path}: segment for ({seg['user']!r}, "
                    f"{seg['timespan']!r}) out of bounds")

    def arrays(self, seg: dict):
        """Zero-copy (codes, values) views for one footer ``pairs``
        entry — no bytes are read until numpy touches them."""
        n = int(seg["n"])
        codes = np.frombuffer(self._mm, np.int64, n,
                              int(seg["codes_off"]))
        values = np.frombuffer(self._mm, np.float64, n,
                               int(seg["values_off"]))
        return codes, values


def open_tilefs(path: str) -> TilefsReader:
    """Open + validate; raises :class:`TilefsError` on anything that
    must not be served (the caller owns the heap fallback)."""
    try:
        return TilefsReader(path)
    except (OSError, struct.error, json.JSONDecodeError,
            KeyError, UnicodeDecodeError) as exc:
        raise TilefsError(f"{path}: unreadable ({exc!r})") from exc


def verify_tilefs(path: str) -> str | None:
    """Deep check for the recovery sweep: everything the reader checks
    PLUS the per-segment payload crcs (this faults in every page, so it
    runs offline, never on the serve path). Returns None when intact,
    else a one-line reason."""
    try:
        r = TilefsReader(path)
    except Exception as exc:
        return f"{type(exc).__name__}: {exc}"
    try:
        for seg in r.pairs:
            codes, values = r.arrays(seg)
            if zlib.crc32(codes.tobytes()) != int(seg["codes_crc"]):
                return (f"codes crc mismatch for ({seg['user']!r}, "
                        f"{seg['timespan']!r})")
            if zlib.crc32(values.tobytes()) != int(seg["values_crc"]):
                return (f"values crc mismatch for ({seg['user']!r}, "
                        f"{seg['timespan']!r})")
    except OSError as exc:
        return f"unreadable payload: {exc}"
    return None


def write_tilefs_from_loaded(dirpath: str, levels: dict) -> list[str]:
    """Write tilefs mirrors for loaded-column levels ({zoom: cols} with
    ``user``/``timespan`` string columns — ``LevelArraysSink.load``'s
    shape). The per-pair split and Morton encoding here must match
    ``TileStore._build_from_levels`` exactly; the shared writer-side
    sort does the rest. Returns the written paths."""
    from heatmap_tpu.tilemath.morton import morton_encode_np

    written = []
    for zoom in sorted(levels):
        cols = levels[zoom]
        users = np.asarray(cols["user"], str)
        tss = np.asarray(cols["timespan"], str)
        codes = morton_encode_np(
            np.asarray(cols["row"], np.int64),
            np.asarray(cols["col"], np.int64))
        values = np.asarray(cols["value"], np.float64)
        pair_key = np.char.add(np.char.add(users, "|"), tss)
        pairs = []
        for pk in np.unique(pair_key):
            sel = pair_key == pk
            user, _, ts = str(pk).partition("|")
            pairs.append((user, ts, codes[sel], values[sel]))
        written.append(write_tilefs(dirpath, int(zoom),
                                    int(cols["coarse_zoom"]), pairs))
    return written
