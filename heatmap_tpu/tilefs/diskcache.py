"""Disk cache tier: rendered tile bytes between the heap LRU and render.

Sits under :class:`heatmap_tpu.serve.cache.TileCache`: on a heap miss
the flight leader consults this directory before rendering, and
write-throughs after — so the single-flight guarantee the heap cache
already provides covers the disk fill too (one render AND one disk
write per key storm-wide).

Keys carry the exact invalidation epochs the serve tier already stamps
(cache key tuple + store generation + delta epoch; synopsis keys embed
the synopsis epoch in the tuple), hashed into a two-level fanout
directory. Entries are self-verifying::

    magic TFSC1 | type u8 (0=bytes, 1=utf-8 str) | length u64 |
    crc32(payload) u32 | payload

A torn or corrupt entry (crash mid-write, bit rot) fails the
length/crc check and is treated as a miss — unlinked and re-rendered,
never served. Writes stage to ``.tmp-*`` + ``os.replace`` under the
``diskcache.write`` fault site (retries=0: a failed fill is just a
skipped optimization, the tile was already rendered). ``sweep()`` runs
at attach time and removes orphan tmps and torn entries left by a
crash; ``_prune`` keeps the directory under ``max_bytes`` by evicting
oldest-access first (mtime is touched on every hit).
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading
import zlib

from heatmap_tpu import faults, obs

_registry = obs.get_registry()
DISK_CACHE_HITS = _registry.counter(
    "disk_cache_hits_total", "Tile renders avoided by the disk tier")
DISK_CACHE_MISSES = _registry.counter(
    "disk_cache_misses_total", "Disk-tier lookups that fell through "
    "to a render")
DISK_CACHE_TORN = _registry.counter(
    "disk_cache_torn_total", "Entries that failed the length/crc check "
    "and were treated as misses")
DISK_CACHE_EVICTIONS = _registry.counter(
    "disk_cache_evictions_total", "Entries pruned to stay under the "
    "byte cap")
DISK_CACHE_BYTES = _registry.gauge(
    "disk_cache_bytes", "Bytes currently held by the disk tier")

_MAGIC = b"TFSC1"
_HEAD_FMT = "=5sBQI"
_HEAD_SIZE = struct.calcsize(_HEAD_FMT)


class DiskTileCache:
    """Size-capped directory of rendered tile payloads.

    ``get``/``put`` take the full invalidation key (any repr-able
    tuple); entries from superseded epochs are never read again and
    age out through the LRU prune rather than via explicit
    invalidation — epoch-in-key makes staleness structurally
    impossible, exactly like the heap cache's generation check.
    """

    def __init__(self, root: str, max_bytes: int = 1 << 30):
        self.root = root
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)
        self.sweep()

    # -- key → path --------------------------------------------------------

    def _path(self, key) -> str:
        digest = hashlib.blake2b(repr(key).encode(),
                                 digest_size=16).hexdigest()
        return os.path.join(self.root, digest[:2], digest)

    # -- read path ---------------------------------------------------------

    def get(self, key):
        """Payload for ``key`` or None; torn entries count as misses
        and are unlinked so the re-render's write-through heals them."""
        path = self._path(key)
        counting = obs.metrics_enabled()
        try:
            with open(path, "rb") as f:
                head = f.read(_HEAD_SIZE)
                if len(head) < _HEAD_SIZE:
                    raise ValueError("short header")
                magic, kind, length, crc = struct.unpack(_HEAD_FMT, head)
                if magic != _MAGIC:
                    raise ValueError("bad magic")
                payload = f.read(length + 1)
                if len(payload) != length:
                    raise ValueError("short payload")
                if zlib.crc32(payload) != crc:
                    raise ValueError("crc mismatch")
        except FileNotFoundError:
            if counting:
                DISK_CACHE_MISSES.inc()
            return None
        except (OSError, ValueError):
            # Torn mid-write or corrupted on disk: a miss, never an
            # error — unlink so the directory doesn't accumulate junk.
            try:
                os.unlink(path)
            except OSError:
                pass
            if counting:
                DISK_CACHE_TORN.inc()
                DISK_CACHE_MISSES.inc()
            return None
        try:
            os.utime(path)  # LRU recency signal for _prune
        except OSError:
            pass
        if counting:
            DISK_CACHE_HITS.inc()
        return payload.decode() if kind == 1 else payload

    # -- write path --------------------------------------------------------

    def put(self, key, value) -> bool:
        """Write-through after a render. Failures (full disk, injected
        ``diskcache.write`` fault) skip the fill and return False — the
        caller already has the rendered bytes in hand."""
        payload = value.encode() if isinstance(value, str) else bytes(value)
        kind = 1 if isinstance(value, str) else 0
        path = self._path(key)
        tmp = os.path.join(os.path.dirname(path),
                           f".tmp-{os.path.basename(path)}")
        try:
            faults.check("diskcache.write", key=os.path.basename(path))
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(struct.pack(_HEAD_FMT, _MAGIC, kind,
                                    len(payload), zlib.crc32(payload)))
                f.write(payload)
            os.replace(tmp, path)
        except (OSError, faults.InjectedFault):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        self._prune()
        return True

    # -- maintenance -------------------------------------------------------

    def _entries(self):
        """[(mtime, size, path)] for every published entry."""
        out = []
        for d in os.listdir(self.root):
            sub = os.path.join(self.root, d)
            if not os.path.isdir(sub):
                continue
            for name in os.listdir(sub):
                full = os.path.join(sub, name)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                out.append((st.st_mtime, st.st_size, full))
        return out

    def _prune(self):
        """Evict oldest-access entries until under ``max_bytes``."""
        with self._lock:
            entries = self._entries()
            total = sum(size for _, size, _ in entries)
            if obs.metrics_enabled():
                DISK_CACHE_BYTES.set(total)
            if total <= self.max_bytes:
                return
            evicted = 0
            for _, size, full in sorted(entries):
                if total <= self.max_bytes:
                    break
                try:
                    os.unlink(full)
                except OSError:
                    continue
                total -= size
                evicted += 1
            if evicted and obs.metrics_enabled():
                DISK_CACHE_EVICTIONS.inc(evicted)
                DISK_CACHE_BYTES.set(total)

    def sweep(self) -> int:
        """Crash recovery: drop orphan ``.tmp-*`` stagings and torn
        entries so a restarted server never trips on them mid-serve.
        Returns the number of files removed."""
        removed = 0
        for d in sorted(os.listdir(self.root)):
            sub = os.path.join(self.root, d)
            if not os.path.isdir(sub):
                continue
            for name in sorted(os.listdir(sub)):
                full = os.path.join(sub, name)
                doomed = name.startswith(".tmp-")
                if not doomed:
                    try:
                        with open(full, "rb") as f:
                            head = f.read(_HEAD_SIZE)
                            magic, _, length, crc = struct.unpack(
                                _HEAD_FMT, head)
                            payload = f.read(length + 1)
                        doomed = (magic != _MAGIC
                                  or len(payload) != length
                                  or zlib.crc32(payload) != crc)
                    except (OSError, struct.error):
                        doomed = True
                if doomed:
                    try:
                        os.unlink(full)
                        removed += 1
                    except OSError:
                        pass
        return removed

    def stats(self) -> dict:
        entries = self._entries()
        return {"root": self.root, "entries": len(entries),
                "bytes": int(sum(s for _, s, _ in entries)),
                "max_bytes": self.max_bytes}
