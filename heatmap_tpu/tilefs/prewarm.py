"""Popularity-driven cache pre-warming from the http_request event log.

Tile traffic is Zipf-shaped (the load generator models it explicitly:
80/20 over a shuffled universe), so yesterday's head predicts today's:
replaying the top-K most-popular tile paths into a freshly started (or
just-reloaded) backend collapses cold-start p99 to warm-path latency
for the requests that dominate the distribution.

``build_plan`` folds one or more JSONL event logs (``obs.EventLog``
output) into a deterministic ordered plan: per-path scores are
exponentially decayed by *event recency* — position in the log, not
wall-clock, so a fixed log always yields the identical plan on every
backend of a fleet (each one computes it locally from the same file; no
coordination, no clock reads) — ties broken lexically. ``warm`` then
drives the plan through ``ServeApp.handle`` under a time + byte budget,
filling every tier (heap ``TileCache`` and the disk tier behind it) via
the normal render path, and emits one ``prewarm_done`` event plus
``prewarm_*`` metrics.
"""

from __future__ import annotations

import dataclasses
import time

from heatmap_tpu import obs

_registry = obs.get_registry()
PREWARM_KEYS = _registry.counter(
    "prewarm_keys_total", "Plan keys replayed into the caches",
    labelnames=("result",))  # result = warmed | error
PREWARM_BYTES = _registry.counter(
    "prewarm_bytes_total", "Response bytes rendered while pre-warming")
PREWARM_RUNS = _registry.counter(
    "prewarm_runs_total", "Pre-warm passes, by trigger",
    labelnames=("source",))  # source = startup | reload


@dataclasses.dataclass
class PrewarmConfig:
    """Everything a backend needs to warm itself (cli/fleet flags)."""

    events: tuple = ()       # JSONL event-log paths, oldest first
    top_k: int = 64
    half_life: float = 512.0  # decay half-life, in EVENTS (not seconds)
    budget_s: float = 10.0
    budget_bytes: int = 64 << 20


def build_plan(event_paths, *, top_k: int = 64,
               half_life: float = 512.0) -> list[str]:
    """Ordered tile paths to replay: the decayed-frequency head.

    Reads ``http_request`` events from ``event_paths`` (in the given
    order, oldest log first), keeps 2xx tile requests, and scores each
    path by ``sum(0.5 ** (age / half_life))`` where ``age`` counts
    events back from the newest — a purely positional decay, so the
    plan is a deterministic function of the log bytes. Returns at most
    ``top_k`` paths, best first, ties broken by path.
    """
    requests: list[str] = []
    for log_path in event_paths:
        try:
            records = obs.read_events(log_path)
        except OSError:
            continue
        for rec in records:
            if rec.get("event") != "http_request":
                continue
            path = rec.get("path")
            status = rec.get("status", 0)
            if not path or not path.startswith("/tiles/"):
                continue
            if not 200 <= int(status) < 300:
                continue
            requests.append(path.partition("?")[0]
                            + ("?synopsis=1" if "synopsis=1" in path
                               else ""))
    n = len(requests)
    scores: dict[str, float] = {}
    for i, path in enumerate(requests):
        scores[path] = scores.get(path, 0.0) + 0.5 ** ((n - 1 - i)
                                                       / half_life)
    ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
    return [path for path, _ in ranked[: max(0, int(top_k))]]


def warm(app, plan, *, budget_s: float = 10.0,
         budget_bytes: int = 64 << 20, source: str = "startup",
         clock=time.monotonic) -> dict:
    """Replay ``plan`` through ``app.handle`` until done or out of
    budget. Every request goes through the full serve path, so the heap
    cache, the disk tier, and any synopsis decode all fill exactly as a
    real client would fill them. Returns (and emits) the summary."""
    t0 = clock()
    counting = obs.metrics_enabled()
    keys = errors = 0
    nbytes = 0
    exhausted = False
    for path in plan:
        if clock() - t0 >= budget_s or nbytes >= budget_bytes:
            exhausted = True
            break
        try:
            res = app.handle("GET", path)
            status = int(res[0])
            body = res[2] if len(res) > 2 else b""
        except Exception:
            status, body = 599, b""
        if 200 <= status < 300:
            keys += 1
            nbytes += len(body) if body else 0
            if counting:
                PREWARM_KEYS.inc(result="warmed")
        else:
            errors += 1
            if counting:
                PREWARM_KEYS.inc(result="error")
    seconds = clock() - t0
    if counting:
        PREWARM_RUNS.inc(source=source)
        if nbytes:
            PREWARM_BYTES.inc(nbytes)
    obs.emit("prewarm_done", keys=keys, seconds=round(seconds, 6),
             bytes=int(nbytes), errors=errors, planned=len(plan),
             budget_exhausted=exhausted, source=source)
    return {"keys": keys, "planned": len(plan), "seconds": seconds,
            "bytes": int(nbytes), "errors": errors,
            "budget_exhausted": exhausted, "source": source}
