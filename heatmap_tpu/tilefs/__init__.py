"""tilefs: zero-copy serving storage (see docs/tilefs.md).

Three pillars:

- :mod:`heatmap_tpu.tilefs.format`    — the mmap'd columnar per-zoom
  file format (``tilefs-z*.bin``) and its reader/writer/verifier;
- :mod:`heatmap_tpu.tilefs.diskcache` — the size-capped disk tier of
  rendered tile bytes between the heap LRU and on-demand render;
- :mod:`heatmap_tpu.tilefs.prewarm`   — popularity-driven cache
  pre-warming from the ``http_request`` event log.

Numpy-only throughout (the serve-path contract: no jax import, no
backend init — serving must survive the accelerator relay being down).
"""

from heatmap_tpu.tilefs.diskcache import DiskTileCache
from heatmap_tpu.tilefs.format import (SCHEMA, TilefsError, TilefsReader,
                                       list_tilefs, open_tilefs,
                                       sniff_tilefs, tilefs_path,
                                       verify_tilefs, write_tilefs,
                                       write_tilefs_from_loaded)
from heatmap_tpu.tilefs.prewarm import (PrewarmConfig, build_plan, warm)

__all__ = [
    "SCHEMA", "TilefsError", "TilefsReader", "DiskTileCache",
    "PrewarmConfig", "build_plan", "list_tilefs", "open_tilefs",
    "sniff_tilefs", "tilefs_path", "verify_tilefs", "warm",
    "write_tilefs", "write_tilefs_from_loaded",
]
