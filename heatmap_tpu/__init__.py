"""heatmap_tpu — a TPU-native geospatial heatmap aggregation framework.

Re-imagines the capabilities of the reference Spark heatmap job
(reference heatmap.py / tile.py) as a JAX/XLA-first engine.

Shipped subpackages (this list tracks the tree; see SURVEY.md §7 for the
full build plan):

- ``tilemath`` — vectorized Web-Mercator projection, integer tile keys,
  Morton codes (replaces reference tile.py's string ids and scalar trig).
- ``ops`` — dense window-raster histograms (XLA scatter + Pallas MXU
  kernels), fixed-capacity sparse sort+segment-sum aggregation, and
  zoom-pyramid rollups (replaces Spark's reduceByKey/groupByKey
  shuffles, reference heatmap.py:111-112).
- ``pipeline`` — the batch jobs (plain/fast/resumable/bounded), group
  and timespan routing, and the single-sort composite-key cascade
  (reference batchMain, heatmap.py:152-158).
- ``parallel`` — the (data, tile) device mesh, sharded kernels with
  collective merges, and multi-host ingest/egress (reference
  submit-heatmap's Spark scale-out).
- ``io`` — columnar sources (CSV/JSONL/Parquet/HMPB/synthetic,
  Cassandra token ranges, CosmosDB partition ranges), blob + columnar
  sinks, PNG tile trees, offline shard merging (reference
  get_rows/write_heatmap_dataframes, heatmap.py:131-150).
- ``streaming`` — decayed micro-batch rasters (BASELINE config 4).
- ``native`` — C++ host runtime: CSV point codec, cascade-key decoder,
  blob formatters (the role Spark's JVM machinery played).
- ``utils`` — tracing, checkpoint/resume, shard recovery.
"""

__version__ = "0.2.0"

from heatmap_tpu.tilemath import (  # noqa: F401
    Tile,
    column_from_longitude,
    latitude_from_row,
    longitude_from_column,
    row_from_latitude,
    tile_id_from_lat_long,
)
from heatmap_tpu.ops import (  # noqa: F401
    Window,
    aggregate_keys,
    bin_points_window,
    bin_rowcol_window,
    coarsen_raster,
    pyramid_from_raster,
    pyramid_sparse_morton,
    window_from_bounds,
)
