"""heatmap_tpu — a TPU-native geospatial heatmap aggregation framework.

Re-imagines the capabilities of the reference Spark heatmap job
(reference heatmap.py / tile.py) as a JAX/XLA-first engine.

Shipped subpackages (this list tracks the tree; see SURVEY.md §7 for the
full build plan):

- ``tilemath`` — vectorized Web-Mercator projection, integer tile keys,
  Morton codes (replaces reference tile.py's string ids and scalar trig).
- ``ops`` — dense window-raster histograms, fixed-capacity sparse
  sort+segment-sum aggregation, and zoom-pyramid rollups (replaces
  Spark's reduceByKey/groupByKey shuffles, reference heatmap.py:111-112).
"""

__version__ = "0.2.0"

from heatmap_tpu.tilemath import (  # noqa: F401
    Tile,
    column_from_longitude,
    latitude_from_row,
    longitude_from_column,
    row_from_latitude,
    tile_id_from_lat_long,
)
from heatmap_tpu.ops import (  # noqa: F401
    Window,
    aggregate_keys,
    bin_points_window,
    bin_rowcol_window,
    coarsen_raster,
    pyramid_from_raster,
    pyramid_sparse_morton,
    window_from_bounds,
)
