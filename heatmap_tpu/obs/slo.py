"""Declarative SLOs with error-budget burn rates over the event log.

An objective is declared as a spec string (CLI ``--slo``, repeatable):

    NAME:KIND:key=value,key=value,...

Kinds (see docs/observability.md for the full grammar):

- ``latency``    — fraction of ``http_request`` events with
  ``ms <= threshold_ms`` (params: ``threshold_ms`` required,
  ``route`` to filter one route family, ``target``, ``window_s``).
- ``error_rate`` — fraction of ``http_request`` events with
  ``status < 500`` (params: ``target``, ``window_s``, ``route``).
- ``staleness``  — the newest ``delta_applied``/``store_reload`` event
  is at most ``max_age_s`` old (params: ``max_age_s`` required,
  ``target``, ``window_s``; compliance is binary).

``target`` defaults to 0.999 and ``window_s`` to 300. The error budget
is ``1 - target``; the burn rate is ``bad_fraction / budget`` — burn 1.0
spends the budget exactly at the window's pace, burn >1 is a breach and
emits one ``slo_breach`` event per rising edge.

The engine consumes events two ways: live, as the observer hook
``obs.events`` calls on every emitted record (serve installs this via
``--slo``), or offline via :func:`SLOEngine.ingest_log` over a finished
run's JSONL (how the run report folds SLO status in). Both feed the
same bounded in-memory window, so ``/healthz`` never re-reads the log
file on the request path.

No raw clocks here beyond ``time.time`` (events carry wall-clock ``ts``
envelopes); tests/test_obs.py greps this file for banned timing calls.
"""

from __future__ import annotations

import threading
import time
from collections import deque

DEFAULT_TARGET = 0.999
DEFAULT_WINDOW_S = 300.0
KINDS = ("latency", "error_rate", "staleness")
_MAX_BUFFER = 10_000
# Events that mark served data as "fresh" for staleness objectives.
# ingest_tick uses the record's wall-clock ts (not the event-time
# watermark it carries — synthetic/replayed streams stamp epoch-scale
# timestamps): a staleness SLO over an ingest loop breaches when no
# tick has completed within max_age_s.
_FRESHNESS_EVENTS = ("delta_applied", "store_reload", "ingest_tick")


class SLOSpec:
    """One parsed objective (immutable after construction)."""

    __slots__ = ("name", "kind", "target", "window_s", "threshold_ms",
                 "max_age_s", "route")

    def __init__(self, name: str, kind: str, *, target: float = DEFAULT_TARGET,
                 window_s: float = DEFAULT_WINDOW_S,
                 threshold_ms: float | None = None,
                 max_age_s: float | None = None, route: str | None = None):
        if kind not in KINDS:
            raise ValueError(f"unknown SLO kind {kind!r} (one of {KINDS})")
        if not name:
            raise ValueError("SLO name must be non-empty")
        if not (0.0 < target < 1.0):
            raise ValueError(f"target must be in (0, 1), got {target}")
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        if kind == "latency" and threshold_ms is None:
            raise ValueError("latency SLO requires threshold_ms=")
        if kind == "staleness" and max_age_s is None:
            raise ValueError("staleness SLO requires max_age_s=")
        self.name = name
        self.kind = kind
        self.target = float(target)
        self.window_s = float(window_s)
        self.threshold_ms = None if threshold_ms is None else float(
            threshold_ms)
        self.max_age_s = None if max_age_s is None else float(max_age_s)
        self.route = route

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def describe(self) -> dict:
        d = {"name": self.name, "kind": self.kind, "target": self.target,
             "window_s": self.window_s}
        if self.threshold_ms is not None:
            d["threshold_ms"] = self.threshold_ms
        if self.max_age_s is not None:
            d["max_age_s"] = self.max_age_s
        if self.route is not None:
            d["route"] = self.route
        return d


def parse_slo_spec(spec: str) -> SLOSpec:
    """``NAME:KIND:k=v,...`` -> SLOSpec (raises ValueError with the
    offending fragment on bad input)."""
    parts = spec.split(":", 2)
    if len(parts) < 2:
        raise ValueError(
            f"bad SLO spec {spec!r}: want NAME:KIND[:k=v,...]")
    name, kind = parts[0].strip(), parts[1].strip()
    params: dict = {}
    if len(parts) == 3 and parts[2].strip():
        for item in parts[2].split(","):
            if "=" not in item:
                raise ValueError(
                    f"bad SLO param {item!r} in {spec!r} (want key=value)")
            key, value = item.split("=", 1)
            key = key.strip()
            value = value.strip()
            if key == "route":
                params[key] = value
            elif key in ("target", "window_s", "threshold_ms", "max_age_s"):
                params[key] = float(value)
            else:
                raise ValueError(f"unknown SLO param {key!r} in {spec!r}")
    return SLOSpec(name, kind, **params)


class SLOEngine:
    """Evaluates a set of objectives over a bounded event window.

    Feed it live (``observe``, installed as the obs.events observer) or
    offline (``ingest_log``); ``evaluate`` computes per-objective
    compliance + burn rate and emits ``slo_breach`` on rising edges.
    """

    def __init__(self, specs):
        self.specs = list(specs)
        self._lock = threading.Lock()
        self._window: deque = deque(maxlen=_MAX_BUFFER)  # http_request recs
        self._last_fresh: float | None = None  # newest freshness event ts
        self._breaching: set = set()  # objective names currently in breach

    # -- ingestion ---------------------------------------------------------
    def observe(self, rec: dict):
        """Observer hook: called by obs.events.emit for every record."""
        event = rec.get("event")
        if event == "http_request":
            with self._lock:
                self._window.append(
                    (rec.get("ts", 0.0), rec.get("route"),
                     rec.get("status"), rec.get("ms")))
        elif event in _FRESHNESS_EVENTS:
            ts = rec.get("ts", 0.0)
            with self._lock:
                if self._last_fresh is None or ts > self._last_fresh:
                    self._last_fresh = ts

    def ingest_log(self, path: str) -> int:
        """Replay a finished run's JSONL through observe (offline
        folding for the run report). Returns records consumed."""
        from heatmap_tpu.obs.events import read_events

        records = read_events(path)
        for rec in records:
            self.observe(rec)
        return len(records)

    # -- evaluation --------------------------------------------------------
    def _evaluate_one(self, spec: SLOSpec, now: float) -> dict:
        cutoff = now - spec.window_s
        if spec.kind == "staleness":
            with self._lock:
                last = self._last_fresh
            age = None if last is None else max(0.0, now - last)
            # No freshness signal yet = no data, not a breach.
            good = 1 if (age is None or age <= spec.max_age_s) else 0
            total = 0 if age is None else 1
            detail = {"age_s": None if age is None else round(age, 3),
                      "max_age_s": spec.max_age_s}
        else:
            with self._lock:
                rows = [r for r in self._window if r[0] >= cutoff]
            if spec.route is not None:
                rows = [r for r in rows if r[1] == spec.route]
            total = len(rows)
            if spec.kind == "latency":
                rows = [r for r in rows if r[3] is not None]
                total = len(rows)
                good = sum(1 for r in rows if r[3] <= spec.threshold_ms)
                detail = {"threshold_ms": spec.threshold_ms}
            else:  # error_rate
                good = sum(
                    1 for r in rows
                    if r[2] is not None and int(r[2]) < 500)
                detail = {}
        compliance = (good / total) if total else 1.0
        burn = (1.0 - compliance) / spec.budget
        status = {"name": spec.name, "kind": spec.kind,
                  "target": spec.target, "window_s": spec.window_s,
                  "total": total, "good": good,
                  "compliance": round(compliance, 6),
                  "budget": round(spec.budget, 6),
                  "burn_rate": round(burn, 3),
                  "breaching": burn > 1.0}
        status.update(detail)
        return status

    def evaluate(self, now: float | None = None) -> list:
        """Status dict per objective; emits slo_breach on rising edges."""
        if now is None:
            now = time.time()
        statuses = [self._evaluate_one(spec, now) for spec in self.specs]
        edges = []
        with self._lock:
            for st in statuses:
                name = st["name"]
                if st["breaching"] and name not in self._breaching:
                    self._breaching.add(name)
                    edges.append(st)
                elif not st["breaching"] and name in self._breaching:
                    self._breaching.discard(name)
        if edges:
            from heatmap_tpu.obs import events

            for st in edges:
                events.emit("slo_breach", slo=st["name"], kind=st["kind"],
                            burn_rate=st["burn_rate"],
                            compliance=st["compliance"],
                            target=st["target"], window_s=st["window_s"])
        return statuses

    def status(self, now: float | None = None) -> dict:
        """Folded view for /healthz and the run report."""
        statuses = self.evaluate(now=now)
        breaching = [st["name"] for st in statuses if st["breaching"]]
        return {"objectives": statuses, "breaching": breaching,
                "ok": not breaching}

    def burn_snapshot(self, now: float | None = None) -> dict:
        """Machine-readable burn fractions: ``{objective_name: burn}``.

        A pure read over the same evaluation as ``status`` but with no
        breach-edge bookkeeping and no event emission — safe to call
        from a controller poll loop or a health probe at any frequency.
        Burn 1.0 spends the error budget exactly at the window's pace;
        >1.0 is a breach.
        """
        if now is None:
            now = time.time()
        return {spec.name: self._evaluate_one(spec, now)["burn_rate"]
                for spec in self.specs}

    def reset(self):
        with self._lock:
            self._window.clear()
            self._last_fresh = None
            self._breaching.clear()


# -- process-wide default engine ------------------------------------------

_engine: SLOEngine | None = None


def set_engine(engine: SLOEngine | None):
    """Install (or clear) the default engine and wire it as the event
    observer so live emission feeds the evaluation window."""
    global _engine
    _engine = engine
    from heatmap_tpu.obs import events

    events._observer = engine.observe if engine is not None else None


def get_engine() -> SLOEngine | None:
    return _engine


def install_specs(specs) -> SLOEngine | None:
    """Parse spec strings and install the resulting engine; a falsy
    spec list clears the engine. Returns the engine (or None)."""
    if not specs:
        set_engine(None)
        return None
    engine = SLOEngine([parse_slo_spec(s) for s in specs])
    set_engine(engine)
    return engine


def slo_status(now: float | None = None) -> dict | None:
    """Default engine's folded status, or None when no engine is
    installed (what /healthz and build_run_report call)."""
    engine = _engine
    if engine is None:
        return None
    return engine.status(now=now)


def burn_values(now: float | None = None) -> dict:
    """Default engine's numeric burn fractions (``{name: burn}``), or
    ``{}`` when no engine is installed — the brownout controller's
    default burn source and the /healthz ``slo_burn`` block."""
    engine = _engine
    if engine is None:
        return {}
    return engine.burn_snapshot(now=now)
