"""Telemetry subsystem: metrics registry, structured events, run report.

Three pillars (see docs/observability.md):

- ``obs.metrics``  — process-wide counters/gauges/histograms with labels
  and a Prometheus-text writer (``--metrics-dir``);
- ``obs.events``   — append-only JSONL run events with a pinned schema
  (``--events``);
- ``obs.report``   — folds tracer + registry + events into
  ``run_report.json`` and a human table (``--report``).

This module owns the shared metric handles (created once on the default
registry — ``registry.reset()`` clears values but keeps these objects
valid) and the convenience recorders instrumentation sites call. Every
recorder is a no-op when neither the registry is enabled nor an event
log installed, so the pipeline pays near-zero cost with telemetry off —
the same discipline as ``utils.trace.stage_span``. Keep all timing and
stdout inside this package (or utils/trace.py): a tier-1 test greps the
instrumented modules for raw ``print(`` / ``time.perf_counter(``.
"""

from __future__ import annotations

import time

from heatmap_tpu.obs import (anomaly, events, incident, metrics, recorder,
                             slo, timeseries, tracing)
from heatmap_tpu.obs.anomaly import AnomalyEngine, WatchSpec, parse_watch_spec
from heatmap_tpu.obs.incident import IncidentManager
from heatmap_tpu.obs.recorder import FlightRecorder
from heatmap_tpu.obs.timeseries import TelemetrySampler, TimeSeriesStore
from heatmap_tpu.obs.events import (EVENT_SCHEMA, EventLog, emit,
                                    get_event_log, read_events,
                                    set_event_log, validate_event)
from heatmap_tpu.obs.metrics import (MetricsRegistry, enable_metrics,
                                     get_registry, metrics_enabled)
from heatmap_tpu.obs.report import (blob_checksum, build_run_report,
                                    format_run_report, write_run_report)
from heatmap_tpu.obs.slo import (SLOEngine, SLOSpec, install_specs,
                                 parse_slo_spec, slo_status)
from heatmap_tpu.obs.tracing import (TraceCollector, current_span,
                                     current_traceparent, disable_tracing,
                                     enable_tracing, get_collector,
                                     parse_traceparent, tracing_enabled)

_T0 = time.monotonic()  # heartbeat uptime origin (~process start)

_registry = get_registry()

# -- shared metric handles (one definition per series, reused everywhere) --
STAGE_SECONDS = _registry.histogram(
    "stage_duration_seconds", "Host wall-clock per tracer span",
    labelnames=("stage",))
STAGE_ITEMS = _registry.counter(
    "stage_items_total", "Items attributed to tracer spans",
    labelnames=("stage",))
POINTS_BINNED = _registry.counter(
    "points_binned_total", "Emissions routed into the cascade",
    labelnames=("backend",))
SOURCE_ROWS = _registry.counter(
    "source_rows_read_total", "Rows yielded by io sources",
    labelnames=("source",))
SINK_BLOBS = _registry.counter(
    "sink_blobs_written_total", "Blobs written by io sinks",
    labelnames=("sink",))
SINK_ROWS = _registry.counter(
    "sink_rows_written_total", "Tile rows written by level-array sinks",
    labelnames=("sink",))
SINK_BYTES = _registry.counter(
    "sink_bytes_written_total", "Bytes written by io sinks",
    labelnames=("sink",))
SHARD_RETRIES = _registry.counter(
    "shard_retries_total", "Shard attempts that raised and were retried")
STREAM_POINTS = _registry.counter(
    "stream_points_total", "Points ingested by HeatmapStream.update")
STREAM_BATCHES = _registry.counter(
    "stream_batches_total", "Batches ingested by HeatmapStream.update")
STREAM_TIME = _registry.gauge(
    "stream_time_seconds", "Decay clock of the live stream state")
STREAM_TICKS = _registry.counter(
    "stream_ticks_total", "run_stream decay ticks observed by the hook")
HOST_PHASE_SECONDS = _registry.gauge(
    "multihost_phase_uptime_seconds",
    "Per-host uptime at each job phase (straggler gap = max-min)",
    labelnames=("phase", "process"))
HOST_LAST_HEARTBEAT = _registry.gauge(
    "multihost_last_heartbeat_ts", "Unix time of each host's last heartbeat",
    labelnames=("process",))
DEVICE_BYTES = _registry.gauge(
    "device_bytes_in_use", "Last sampled device memory in use",
    labelnames=("device",))
ELASTIC_REASSIGNMENTS = _registry.counter(
    "elastic_reassignments_total",
    "Orphaned shards reassigned to surviving hosts (parallel/elastic)")
PARTITION_SKEW = _registry.gauge(
    "partition_skew_ratio",
    "Max/mean sampled shard mass of the last Morton partition plan "
    "(parallel/partition; the load-imbalance signal the planner bounds)")
BOUNDARY_TILES = _registry.counter(
    "cascade_boundary_tiles_total",
    "Straddling parent tiles cross-merged by range-sharded cascades "
    "(the entire cross-shard merge volume of the Morton path)")
SPECULATIVE_LAUNCHES = _registry.counter(
    "speculative_launches_total",
    "Speculative duplicate shard executions by race outcome",
    labelnames=("outcome",))  # outcome = win | lose
DISPATCH_OVERHEAD = _registry.histogram(
    "dispatch_overhead_seconds",
    "Host-side share of one cascade dispatch (routing, padding, plan, "
    "argument prep — everything before the compiled program runs; the "
    "overhead the gspmd one-program dispatch removes)",
    labelnames=("dispatch",))
FEEDER_DEPTH = _registry.gauge(
    "feeder_depth",
    "Device-resident batches queued ahead of the consumer in the "
    "host->device feeder (pipeline/feeder.py; depth > 0 means the next "
    "batch's transfer fully overlapped compute)")
FAULTS_INJECTED = _registry.counter(
    "faults_injected_total", "Faults fired by the injection plane",
    labelnames=("site",))
IO_RETRIES = _registry.counter(
    "io_retries_total", "I/O operations retried by faults.retry",
    labelnames=("site",))
INCIDENTS_TOTAL = _registry.counter(
    "incidents_total", "Incident bundles flushed, by trigger edge",
    labelnames=("trigger",))
RECORDER_DROPPED = _registry.counter(
    "recorder_dropped_total",
    "Flight-recorder ring evictions (spans + events)")
ANOMALIES_TOTAL = _registry.counter(
    "anomalies_total",
    "Anomaly-detector rising edges, by watch spec",
    labelnames=("watch",))
PROCESS_UPTIME = _registry.gauge(
    "process_uptime_seconds", "Seconds since this process imported obs")
BUILD_INFO = _registry.gauge(
    "heatmap_build_info", "Constant 1; the version label is the payload",
    labelnames=("version",))


def refresh_process_gauges():
    """Stamp process_uptime_seconds and heatmap_build_info{version}.

    Gauge writes no-op while the registry is disabled, so these are
    refreshed at scrape time (serve /metrics, write_prometheus dumps)
    rather than set once at import.
    """
    if not _registry.enabled:
        return
    from heatmap_tpu import __version__

    PROCESS_UPTIME.set(time.monotonic() - _T0)
    BUILD_INFO.set(1, version=__version__)


def telemetry_enabled() -> bool:
    """True when any sink (registry or event log) is live."""
    return _registry.enabled or events._current is not None


class DispatchTimer:
    """Host/device wall-time split for ONE cascade dispatch.

    Splits the cascade's ``stage_duration_seconds`` attribution into
    ``cascade.dispatch.host`` (routing, padding, partition planning,
    argument prep — everything before the compiled program runs) and
    ``cascade.dispatch.device`` (program execution to outputs-ready),
    and feeds ``dispatch_overhead_seconds{dispatch}`` with the host
    share. Construct at the start of the host phase, call
    :meth:`dispatched` when the program has been handed to the
    runtime, :meth:`finished` once outputs are ready (the caller
    blocks on the result in between). Everything no-ops when telemetry
    is off, so the production path pays two global reads. Lives here
    because wall-clock reads are banned outside obs/ and utils/trace
    (tests/test_obs.py grep guards).
    """

    __slots__ = ("dispatch", "enabled", "_t0", "_t1")

    def __init__(self, dispatch: str):
        self.dispatch = dispatch
        self.enabled = telemetry_enabled()
        self._t0 = time.perf_counter() if self.enabled else 0.0
        self._t1 = None

    def dispatched(self) -> None:
        """Host phase over: the compiled program owns the clock now."""
        if self.enabled:
            self._t1 = time.perf_counter()

    def finished(self, items=None):
        """Outputs ready; record both phases. Returns (host_s,
        device_s) when telemetry is on, else None."""
        if not self.enabled or self._t1 is None:
            return None
        t2 = time.perf_counter()
        host_s, device_s = self._t1 - self._t0, t2 - self._t1
        record_stage("cascade.dispatch.host", host_s, items)
        record_stage("cascade.dispatch.device", device_s, items)
        if _registry.enabled:
            DISPATCH_OVERHEAD.observe(host_s, dispatch=self.dispatch)
        return host_s, device_s


def record_stage(stage: str, wall_s: float, items=None, **attrs):
    """Span-close hook: tracer spans feed the registry and event log.

    Called from utils/trace.py on every span exit; must stay cheap when
    telemetry is off (two global reads).
    """
    enabled = _registry.enabled
    log = events._current
    if not enabled and log is None and events._observer is None:
        return
    if enabled:
        STAGE_SECONDS.observe(wall_s, stage=stage)
        if items:
            STAGE_ITEMS.inc(int(items), stage=stage)
    if log is not None or events._observer is not None:
        fields = {k: v for k, v in attrs.items() if v is not None}
        if items:
            fields["items"] = int(items)
        # Through events.emit (not log.emit) so the record is trace-
        # stamped and the SLO observer sees it.
        events.emit("stage_end", stage=stage, wall_s=round(wall_s, 6),
                    **fields)


def device_topology() -> dict:
    """Device manifest for run_start (initialises jax if needed)."""
    import jax

    devices = jax.devices()
    kinds: dict = {}
    for d in devices:
        kinds[d.device_kind] = kinds.get(d.device_kind, 0) + 1
    return {"platform": devices[0].platform,
            "n_devices": len(devices),
            "n_local_devices": jax.local_device_count(),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "device_kinds": kinds}


def sample_device_memory() -> list:
    """Sample memory_stats() from every local device; emits a
    device_memory event (empty samples list on backends without stats,
    e.g. CPU) and sets the per-device gauge."""
    if not telemetry_enabled():
        return []
    import jax

    samples = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        samples.append({
            "device": int(d.id),
            "platform": str(d.platform),
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
        })
        DEVICE_BYTES.set(samples[-1]["bytes_in_use"],
                         device=str(samples[-1]["device"]))
    emit("device_memory", samples=samples)
    return samples


def heartbeat(phase: str, process: int | None = None):
    """Per-host liveness mark for multihost phases. Timing lives here so
    parallel/multihost.py stays free of raw clocks.

    The ``multihost.heartbeat`` fault site models a *lost* heartbeat: an
    injected fault suppresses the gauge/event update without failing the
    caller, so the staleness monitors (``heartbeat_ages`` /
    ``check_heartbeats``) see exactly what a dead host would produce.

    ``process`` overrides the host identity (default: this JAX
    process). The elastic driver's simulated hosts beat with their own
    ids; the fault key then becomes ``p<process>`` so a chaos spec can
    kill exactly one simulated host's heartbeats
    (``multihost.heartbeat@p2=999``) while phase-keyed rules keep
    matching real multihost beats.
    """
    if not telemetry_enabled():
        return
    from heatmap_tpu import faults

    try:
        faults.check("multihost.heartbeat",
                     key=phase if process is None else f"p{process}")
    except faults.InjectedFault:
        return  # heartbeat lost in transit; liveness gauges go stale
    import jax

    pi = jax.process_index() if process is None else int(process)
    count = jax.process_count()
    uptime = time.monotonic() - _T0
    HOST_PHASE_SECONDS.set(uptime, phase=phase, process=str(pi))
    HOST_LAST_HEARTBEAT.set(time.time(), process=str(pi))
    fields = {}
    tp = tracing.current_traceparent()
    if tp is not None:
        # Cross-process propagation: a collector on another host can
        # continue this trace by passing the header to begin_span.
        fields["traceparent"] = tp
    emit("heartbeat", process_index=pi, process_count=count,
         phase=phase, uptime_s=round(uptime, 3), **fields)


def heartbeat_ages(now: float | None = None) -> dict:
    """Seconds since each process's last heartbeat, ``{process: age_s}``.

    Read from the ``multihost_last_heartbeat_ts`` gauge; empty when the
    registry is off or no heartbeat has landed yet. ``now`` overrides
    wall-clock for tests.
    """
    if not _registry.enabled:
        return {}
    if now is None:
        now = time.time()
    return {key[0]: now - ts
            for key, ts in HOST_LAST_HEARTBEAT.samples().items()}


def record_retry(shard: int, attempt: int, error: BaseException):
    if not telemetry_enabled():
        return
    SHARD_RETRIES.inc()
    emit("retry", shard=int(shard), attempt=int(attempt),
         error=repr(error))


def record_recovery(shard: int, attempts: int):
    if not telemetry_enabled():
        return
    emit("recovery", shard=int(shard), attempts=int(attempts))


def record_fault(site: str, seq: int, key=None, rule: str | None = None):
    """One injected fault fired by the faults plane (seq is the plane's
    own monotonic injection counter, replayable from the event log)."""
    if not telemetry_enabled():
        return
    FAULTS_INJECTED.inc(site=site)
    fields = {}
    if key is not None:
        fields["key"] = str(key)
    if rule is not None:
        fields["rule"] = rule
    emit("fault_injected", site=site, fault_seq=int(seq), **fields)


def record_io_retry(site: str):
    if not telemetry_enabled():
        return
    IO_RETRIES.inc(site=site)


def record_shard_orphaned(shard, host, reason: str | None = None):
    """A stale host's unfinished shard was marked orphaned
    (parallel/elastic.py failover)."""
    if not telemetry_enabled():
        return
    fields = {"reason": reason} if reason else {}
    emit("shard_orphaned", shard=str(shard), host=str(host), **fields)


def record_shard_reassigned(shard, from_host, to_host):
    """An orphaned shard was handed to a surviving host; paired 1:1
    with record_shard_orphaned and counted in
    elastic_reassignments_total."""
    if not telemetry_enabled():
        return
    ELASTIC_REASSIGNMENTS.inc()
    emit("shard_reassigned", shard=str(shard), from_host=str(from_host),
         to_host=str(to_host))


def record_partition_planned(plan, boundary_tiles=None):
    """A Morton partition plan was built for a cascade dispatch.

    Sets partition_skew_ratio to the plan's max/mean sampled shard mass
    and, when the caller passes the per-pyramid boundary-tile count,
    folds it into cascade_boundary_tiles_total.
    """
    if not telemetry_enabled():
        return
    PARTITION_SKEW.set(plan.skew_ratio)
    fields = {}
    if boundary_tiles is not None:
        BOUNDARY_TILES.inc(int(boundary_tiles))
        fields["boundary_tiles"] = int(boundary_tiles)
    emit("partition_planned",
         n_shards=plan.n_shards,
         splits=[int(s) for s in plan.splits],
         sampled_points=plan.sampled_points,
         balance_factor=plan.balance_factor,
         max_shard_mass=max(plan.shard_mass) if plan.shard_mass else 0.0,
         mean_shard_mass=(sum(plan.shard_mass) / len(plan.shard_mass)
                          if plan.shard_mass else 0.0),
         skew_ratio=plan.skew_ratio,
         resplits=plan.resplits,
         degenerate=plan.degenerate,
         fingerprint=plan.fingerprint,
         **fields)


def record_speculative_launch(shard, host, runtime_s=None,
                              threshold_s=None):
    """A duplicate execution of a straggling shard was launched on an
    idle host (first-completion-wins)."""
    if not telemetry_enabled():
        return
    fields = {}
    if runtime_s is not None:
        fields["runtime_s"] = round(float(runtime_s), 3)
    if threshold_s is not None:
        fields["threshold_s"] = round(float(threshold_s), 3)
    emit("speculative_launch", shard=str(shard), host=str(host), **fields)


def record_speculative_result(shard, winner, loser=None, won: bool = False,
                              quarantined: str | None = None):
    """Resolve one speculative race: increments
    speculative_launches_total{outcome} and, when the duplicate beat
    the original, emits the speculative_win event naming the quarantined
    loser artifact."""
    if not telemetry_enabled():
        return
    SPECULATIVE_LAUNCHES.inc(outcome="win" if won else "lose")
    if won:
        fields = {}
        if loser is not None:
            fields["loser"] = str(loser)
        if quarantined is not None:
            fields["quarantined"] = str(quarantined)
        emit("speculative_win", shard=str(shard), winner=str(winner),
             **fields)


__all__ = [
    "AnomalyEngine",
    "DISPATCH_OVERHEAD", "DispatchTimer",
    "EVENT_SCHEMA", "EventLog", "FEEDER_DEPTH", "FlightRecorder",
    "IncidentManager",
    "MetricsRegistry", "SLOEngine", "SLOSpec",
    "TelemetrySampler", "TimeSeriesStore", "WatchSpec",
    "anomaly", "parse_watch_spec", "timeseries",
    "TraceCollector", "blob_checksum", "build_run_report", "current_span",
    "current_traceparent", "device_topology", "disable_tracing", "emit",
    "enable_metrics", "enable_tracing", "events", "format_run_report",
    "get_collector", "get_event_log", "get_registry", "heartbeat",
    "heartbeat_ages", "incident", "install_specs", "metrics",
    "metrics_enabled",
    "parse_slo_spec", "parse_traceparent", "read_events", "record_fault",
    "record_io_retry", "record_partition_planned", "record_recovery",
    "record_retry",
    "record_shard_orphaned", "record_shard_reassigned",
    "record_speculative_launch", "record_speculative_result",
    "record_stage", "recorder", "refresh_process_gauges",
    "sample_device_memory", "set_event_log",
    "slo", "slo_status", "telemetry_enabled", "tracing", "tracing_enabled",
    "validate_event", "write_run_report",
]
