"""Flight recorder: always-on bounded rings of completed spans/events.

Head sampling (obs/tracing.py) decides keep-or-drop once at the trace
root, so a 503 burst or a straggler inside an unsampled trace is lost
forever. The :class:`FlightRecorder` closes that gap with **tail-based
retention**: while installed it keeps the last N *completed* spans and
events per subsystem at full fidelity regardless of the head decision
(unsampled trees run as "shadow" spans — real Span objects flagged so
they never reach the collector or render a sampled traceparent), and
when a tree turns out to matter — an error, a 5xx, an injected fault,
or latency past the tail threshold — :meth:`FlightRecorder.promote`
copies the whole tree out of the ring into the trace collector exactly
as if it had been head-sampled (records are the same ``to_record``
dicts, so promotion is byte-for-byte), dedup'd against already-sampled
roots (sampled spans are never shadow, so there is nothing to copy).

Integration mirrors the rest of obs: one module-global hook per
integration point (``tracing._recorder`` for span routing,
``events._recorder`` for the event ring + incident trigger dispatch),
all None unless :func:`install` wired them, so the off path stays one
global read. No clocks live here — spans carry their own start/dur
and event records carry wall-clock ``ts`` (tests/test_obs.py greps
this file for banned timing calls).
"""

from __future__ import annotations

import threading
from collections import deque

DEFAULT_MAX_SPANS = 256
DEFAULT_MAX_EVENTS = 512


class FlightRecorder:
    """Bounded, lock-cheap ring of completed spans/events per subsystem.

    ``max_spans`` / ``max_events`` bound each subsystem's ring (the
    subsystem is the span name's first dotted segment, e.g.
    ``serve.request`` -> ``serve``; for events the name's first ``_``
    token, e.g. ``http_request`` -> ``http``). Evictions are counted in
    ``dropped`` (and the ``recorder_dropped_total`` counter when the
    registry is live) — the ring never grows without bound.
    """

    def __init__(self, max_spans: int = DEFAULT_MAX_SPANS,
                 max_events: int = DEFAULT_MAX_EVENTS,
                 tail_latency_s: float | None = None):
        if max_spans <= 0 or max_events <= 0:
            raise ValueError("ring capacities must be positive")
        self.max_spans = int(max_spans)
        self.max_events = int(max_events)
        self.tail_latency_s = (None if tail_latency_s is None
                               else float(tail_latency_s))
        self._lock = threading.Lock()
        self._span_rings: dict[str, deque] = {}
        self._event_rings: dict[str, deque] = {}
        # trace_id -> [(shadow, rec), ...] for every span still in a
        # ring (evictions remove their entry, so promotion only ever
        # copies what the ring actually holds).
        self._by_trace: dict[str, list] = {}
        self._promote: set = set()    # live trees being routed out
        self._promoted: set = set()   # dedup: one promotion per trace
        self.dropped = 0
        self.promoted_spans = 0

    # -- capture -----------------------------------------------------------
    @staticmethod
    def _span_subsystem(name: str) -> str:
        return name.split(".", 1)[0]

    @staticmethod
    def _event_subsystem(event: str) -> str:
        return event.split("_", 1)[0]

    def record_span(self, span):
        """Span-close hook (tracing.end_span): ring the completed span;
        forward it live when its tree was already promoted."""
        rec = span.to_record()
        shadow = span.shadow
        entry = (shadow, rec)
        forward = False
        evicted = 0
        with self._lock:
            ring = self._span_rings.get(self._span_subsystem(span.name))
            if ring is None:
                ring = deque()
                self._span_rings[self._span_subsystem(span.name)] = ring
            if len(ring) >= self.max_spans:
                old = ring.popleft()
                evicted = 1
                peers = self._by_trace.get(old[1]["trace_id"])
                if peers is not None:
                    try:
                        peers.remove(old)
                    except ValueError:
                        pass
                    if not peers:
                        del self._by_trace[old[1]["trace_id"]]
            ring.append(entry)
            self._by_trace.setdefault(rec["trace_id"], []).append(entry)
            if shadow and rec["trace_id"] in self._promote:
                forward = True
        if evicted:
            self._count_dropped(evicted)
        if forward:
            self._forward([rec])

    def record_event(self, rec: dict):
        """Event hook (via _dispatch_event): ring the record; an
        injected fault promotes its ambient tree."""
        event = rec.get("event", "")
        evicted = 0
        with self._lock:
            ring = self._event_rings.get(self._event_subsystem(event))
            if ring is None:
                ring = deque()
                self._event_rings[self._event_subsystem(event)] = ring
            if len(ring) >= self.max_events:
                ring.popleft()
                evicted = 1
            ring.append(dict(rec))
        if evicted:
            self._count_dropped(evicted)
        if event == "fault_injected":
            trace_id = rec.get("trace_id")
            if trace_id:
                self.promote(trace_id)

    def _count_dropped(self, n: int):
        self.dropped += n
        from heatmap_tpu.obs import RECORDER_DROPPED

        RECORDER_DROPPED.inc(n)

    # -- tail-based retention ----------------------------------------------
    def promote(self, trace_id: str) -> int:
        """Copy a tree's shadow spans from the ring into the collector
        as if head-sampled. Idempotent per trace (the dedup against
        already-promoted and head-sampled roots); spans that complete
        after promotion are forwarded live. Returns spans copied now."""
        with self._lock:
            if trace_id in self._promoted:
                return 0
            self._promoted.add(trace_id)
            self._promote.add(trace_id)
            recs = [rec for shadow, rec in self._by_trace.get(trace_id, ())
                    if shadow]
        if recs:
            self._forward(recs)
        return len(recs)

    def _forward(self, recs):
        from heatmap_tpu.obs import tracing

        collector = tracing.get_collector()
        if collector is None:
            return
        for rec in recs:
            collector.add_record(rec)
        self.promoted_spans += len(recs)

    # -- snapshots (incident bundles, tests) -------------------------------
    def span_records(self) -> list:
        """Every span currently ringed, oldest-first per subsystem."""
        with self._lock:
            return [rec for sub in sorted(self._span_rings)
                    for _shadow, rec in self._span_rings[sub]]

    def event_records(self) -> list:
        """Every event currently ringed, ordered by envelope (ts, seq)
        so the bundle tail reads like the log it came from."""
        with self._lock:
            recs = [rec for sub in sorted(self._event_rings)
                    for rec in self._event_rings[sub]]
        recs.sort(key=lambda r: (r.get("ts", 0.0), r.get("seq", -1)))
        return recs

    def stats(self) -> dict:
        with self._lock:
            return {
                "max_spans": self.max_spans,
                "max_events": self.max_events,
                "tail_latency_s": self.tail_latency_s,
                "spans": sum(len(r) for r in self._span_rings.values()),
                "events": sum(len(r) for r in self._event_rings.values()),
                "subsystems": sorted(set(self._span_rings)
                                     | set(self._event_rings)),
                "dropped": self.dropped,
                "promoted_traces": len(self._promoted),
                "promoted_spans": self.promoted_spans,
            }

    def clear(self):
        with self._lock:
            self._span_rings.clear()
            self._event_rings.clear()
            self._by_trace.clear()
            self._promote.clear()
            self._promoted.clear()
            self.dropped = 0
            self.promoted_spans = 0


# -- module state / hooks ---------------------------------------------------

_recorder: FlightRecorder | None = None
# Installed by obs.incident.set_manager: sees every emitted event record
# (trigger detection) without events.py importing either module.
_incident_hook = None


def _dispatch_event(rec: dict):
    """The single events._recorder hook: feed the ring, then the
    incident trigger engine."""
    rcd = _recorder
    if rcd is not None:
        rcd.record_event(rec)
    hook = _incident_hook
    if hook is not None:
        hook(rec)


def _sync_hooks():
    """Point the tracing/events hooks at current state (None when
    neither a recorder nor an incident manager is installed, restoring
    the zero-cost off path)."""
    from heatmap_tpu.obs import events, tracing

    events._recorder = (_dispatch_event if (_recorder is not None
                                            or _incident_hook is not None)
                        else None)
    tracing._recorder = _recorder


def install(recorder: FlightRecorder | None):
    """Install (or clear, with None) the process-wide flight recorder
    and wire the tracing/events hooks."""
    global _recorder
    _recorder = recorder
    _sync_hooks()


def get_recorder() -> FlightRecorder | None:
    return _recorder


def maybe_promote(span=None, *, status=None, error: bool = False,
                  ms: float | None = None,
                  trace_id: str | None = None) -> bool:
    """Promote the (ambient or given) tree when it completed badly:
    an error, a 5xx status, or latency past the recorder's tail
    threshold. No-op (False) when no recorder is installed or nothing
    qualified. Call *before* end_span on the root so the root itself
    rides the live-forward path."""
    recorder = _recorder
    if recorder is None:
        return False
    if trace_id is None:
        if span is None:
            from heatmap_tpu.obs import tracing

            span = tracing._current.get()
        trace_id = getattr(span, "trace_id", None)
    if trace_id is None:
        return False
    bad = error or (status is not None and int(status) >= 500)
    if not bad and ms is not None and recorder.tail_latency_s is not None:
        bad = (ms / 1000.0) >= recorder.tail_latency_s
    if not bad:
        return False
    recorder.promote(trace_id)
    return True
