"""Fold tracer + metrics registry + event log into one run report.

``build_run_report`` produces the ``run_report.json`` artifact: the run
manifest (from ``run_start``/``run_end``), per-stage wall-clock with
backend attribution (tracer spans + ``backend_resolved`` events), a full
metrics snapshot, the last device-memory sample, and any warnings (e.g.
the profiler being unavailable). ``format_run_report`` renders the
human-readable table that supersedes the ``--profile``-only stderr dump.

Timings here are HOST spans: under jit, device work is asynchronous, so
a stage's wall-clock measures until the host blocks on a result, not
device occupancy (see utils/trace.py and docs/observability.md).
"""

from __future__ import annotations

import json
import os
import zlib

REPORT_SCHEMA = "heatmap-tpu.run_report.v1"


def blob_checksum(blobs: dict) -> str:
    """Order-independent crc32 fingerprint of a blob dict, for run_end:
    two runs produced identical output iff the checksums match."""
    crc = 0
    for key in sorted(blobs):
        value = blobs[key]
        if not isinstance(value, str):
            value = json.dumps(value, sort_keys=True, default=str)
        crc = zlib.crc32(f"{key}\x00{value}\x01".encode(), crc)
    return f"crc32:{crc:08x}"


def build_run_report(tracer=None, registry=None,
                     events_path: str | None = None) -> dict:
    """Assemble the report dict from whichever sources are available.

    When tracing is live its collector summary folds in as ``trace``;
    when an SLO engine is installed its status folds in as ``slo``
    (evaluated over whatever the engine has observed/ingested).
    """
    report: dict = {"schema": REPORT_SCHEMA}
    warnings: list = []

    if tracer is not None:
        stages = {}
        for name, rec in sorted(tracer.report().items()):
            stages[name] = {
                "count": rec["count"],
                "total_s": round(rec["total_s"], 6),
                "mean_s": round(rec["mean_s"], 6),
                "max_s": round(rec["max_s"], 6),
                "items": rec["items"],
                "items_per_s": (round(rec["items_per_s"])
                                if rec["items_per_s"] else None),
            }
        report["stages"] = stages
        pw = getattr(tracer, "profiler_warning", None)
        if pw:
            warnings.append(pw)

    if registry is not None:
        report["metrics"] = registry.snapshot()

    if events_path and os.path.exists(events_path):
        from heatmap_tpu.obs.events import read_events

        records = read_events(events_path)
        by_type: dict = {}
        for rec in records:
            by_type[rec.get("event", "?")] = (
                by_type.get(rec.get("event", "?"), 0) + 1)
        events_summary = {"path": events_path, "count": len(records),
                          "by_type": by_type}
        report["events"] = events_summary

        run: dict = {}
        backends = []
        last_mem = None
        for rec in records:
            ev = rec.get("event")
            if ev == "run_start":
                run["run_id"] = rec.get("run_id")
                run["started_ts"] = rec.get("ts")
                run["backend"] = rec.get("backend")
                run["devices"] = rec.get("devices")
                run["config"] = rec.get("config")
            elif ev == "run_end":
                for k in ("status", "blobs", "rows", "levels", "checksum",
                          "seconds", "error"):
                    if k in rec:
                        run[k] = rec[k]
            elif ev == "backend_resolved":
                backends.append({k: rec[k] for k in
                                 ("requested", "resolved", "reason",
                                  "weighted", "data_parallel", "n_emissions")
                                 if k in rec})
            elif ev == "device_memory":
                last_mem = rec.get("samples")
            elif ev == "profiler_unavailable":
                warnings.append(f"profiler unavailable: {rec.get('error')}")
        if run:
            report["run"] = run
        if backends:
            report["backends"] = backends
        if last_mem is not None:
            report["device_memory"] = last_mem

    from heatmap_tpu.obs import slo, tracing

    collector = tracing.get_collector()
    if collector is not None:
        report["trace"] = collector.summary()
    slo_state = slo.slo_status()
    if slo_state is not None:
        report["slo"] = slo_state

    if warnings:
        report["warnings"] = warnings
    return report


def write_run_report(path: str, report: dict):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, default=str)
        f.write("\n")
    os.replace(tmp, path)


def format_run_report(report: dict) -> str:
    """Human-readable rendering: run summary, stage table, warnings."""
    lines = ["run report"]
    run = report.get("run") or {}
    if run:
        head = [f"run_id={run.get('run_id', '?')}",
                f"status={run.get('status', '?')}"]
        if run.get("seconds") is not None:
            head.append(f"seconds={run['seconds']}")
        if run.get("blobs") is not None:
            head.append(f"blobs={run['blobs']}")
        if run.get("rows") is not None:
            head.append(f"rows={run['rows']}")
        if run.get("checksum"):
            head.append(f"checksum={run['checksum']}")
        lines.append("  " + "  ".join(head))
    for res in report.get("backends", ()):
        lines.append(
            "  cascade backend: "
            f"{res.get('requested', '?')} -> {res.get('resolved', '?')}"
            + (f" ({res['reason']})" if res.get("reason") else ""))

    stages = report.get("stages") or {}
    if stages:
        lines.append(f"{'stage':<28}{'count':>7}{'total_s':>10}"
                     f"{'mean_s':>10}{'max_s':>10}  items/s")
        for name, rec in sorted(stages.items()):
            ips = (f"{rec['items_per_s']:,}" if rec.get("items_per_s")
                   else "-")
            lines.append(f"{name:<28}{rec['count']:>7}"
                         f"{rec['total_s']:>10.3f}{rec['mean_s']:>10.4f}"
                         f"{rec['max_s']:>10.4f}  {ips}")
    else:
        lines.append("  (no stage spans recorded)")

    trace = report.get("trace")
    if trace:
        lines.append(f"  traces: {trace.get('n_traces', 0)} "
                     f"({trace.get('n_spans', 0)} spans)")
        for root in trace.get("roots", ()):
            lines.append(f"    {root['name']:<26}{root['wall_s']:>10.3f}s"
                         f"  spans={root['n_spans']}")
    slo_state = report.get("slo")
    if slo_state:
        for obj in slo_state.get("objectives", ()):
            flag = "BREACH" if obj.get("breaching") else "ok"
            lines.append(
                f"  slo {obj['name']:<22}{flag:>7}  "
                f"compliance={obj.get('compliance')} "
                f"burn={obj.get('burn_rate')}x")

    mem = report.get("device_memory")
    if mem:
        for s in mem:
            lines.append(
                f"  device {s.get('device')}: "
                f"{s.get('bytes_in_use', 0):,} bytes in use "
                f"(peak {s.get('peak_bytes_in_use', 0):,})")
    for w in report.get("warnings", ()):
        lines.append(f"  WARNING: {w}")
    return "\n".join(lines)
