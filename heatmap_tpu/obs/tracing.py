"""Hierarchical distributed tracing: span trees over the flat tracer.

PR 2's telemetry answers "how much time did stage X take in aggregate";
this module answers "where did *this* request or *this* delta apply
spend its time". Every recorded span carries an identity triple
(``trace_id`` / ``span_id`` / ``parent_id``) and spans nest through a
``contextvars.ContextVar``, so one serve request or one journaled apply
produces a single connected tree even when the work hops threads
(``context_bound`` re-binds the ambient span into pool workers, which
otherwise start with an empty context).

Design points:

- **Root-on-demand.** A span opened with no ambient parent becomes the
  root of a new trace; the sampling decision (``sample`` probability,
  or an incoming ``traceparent``'s flags) is made once at the root and
  inherited by every descendant. Unsampled roots install a sentinel so
  descendants are near-free no-ops rather than new roots.
- **Zero-cost when off.** The hot-path guard is one module-global read
  (``_on``); ``utils.trace`` and ``obs.events`` integrate through
  hooks installed by :func:`enable_tracing` and removed by
  :func:`disable_tracing`, so neither pays an import or an attribute
  chain while tracing is disabled. Blob output is pinned byte-identical
  with tracing on vs off (tests/test_obs.py).
- **W3C-style propagation.** ``current_traceparent()`` renders the
  ambient span as ``00-{trace_id}-{span_id}-{flags}``; the serve tier
  accepts the same header on requests and multihost heartbeats carry it
  as an event field, so cross-process trees share one trace_id.
- **Chrome/Perfetto export.** ``export_chrome`` writes the collected
  spans as trace-event JSON (``ph: "X"`` complete events, microsecond
  ``ts``/``dur``) loadable in ``chrome://tracing`` / Perfetto and by
  ``tools/trace_analyze.py`` (critical path + self-time attribution).

All timing goes through ``_now_s`` — the module's single sanctioned
clock site (tests/test_obs.py greps this file).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import threading
import time
import uuid

# Hard cap on buffered finished spans; beyond it spans are counted as
# dropped instead of growing without bound (a long-lived serve process
# with sample=1.0 would otherwise leak).
MAX_SPANS = 100_000

TRACEPARENT_VERSION = "00"
FLAG_SAMPLED = 0x01


def _now_s() -> float:
    return time.perf_counter()  # sanctioned: the module's only clock site


class Span:
    """One node of a trace tree. Identity is fixed at creation; the
    duration is fixed by :meth:`finish` (collector-relative monotonic
    seconds, exported as microseconds)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_s",
                 "dur_s", "attrs", "tid", "shadow", "_token")

    def __init__(self, name: str, trace_id: str, parent_id: str | None,
                 attrs: dict | None = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.start_s = _now_s()
        self.dur_s = 0.0
        self.attrs = attrs or {}
        self.tid = threading.get_ident()
        # Shadow spans run under an unsampled root while the flight
        # recorder is installed: full fidelity into the ring, never the
        # collector (unless the tree is promoted), flags 00 on the wire.
        self.shadow = False
        self._token = None

    def to_record(self) -> dict:
        """Plain-dict form (what export/analysis consume)."""
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start_s": self.start_s, "dur_s": self.dur_s,
                "tid": self.tid, "attrs": dict(self.attrs)}


class _NotSampled:
    """Contextvar sentinel under an unsampled root: descendants see it
    and no-op instead of opening fresh roots."""

    __slots__ = ("trace_id", "span_id", "_token")

    def __init__(self, trace_id: str | None = None,
                 span_id: str | None = None):
        self.trace_id = trace_id or uuid.uuid4().hex
        self.span_id = span_id or uuid.uuid4().hex[:16]
        self._token = None


def chrome_doc(records, t0: float = 0.0) -> dict:
    """Render span records as a Chrome/Perfetto trace-event document
    (shared by the collector export and incident bundles)."""
    pid = os.getpid()
    events = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "heatmap_tpu"},
    }]
    for rec in records:
        args = {"trace_id": rec["trace_id"],
                "span_id": rec["span_id"],
                "parent_id": rec["parent_id"]}
        for k, v in rec["attrs"].items():
            args[k] = v if isinstance(v, (int, float, bool, str,
                                          type(None))) else str(v)
        events.append({
            "name": rec["name"], "cat": "heatmap", "ph": "X",
            "ts": round((rec["start_s"] - t0) * 1e6, 3),
            "dur": round(rec["dur_s"] * 1e6, 3),
            "pid": pid, "tid": rec["tid"], "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


class TraceCollector:
    """Thread-safe buffer of finished spans plus the sampling policy."""

    def __init__(self, sample: float = 1.0, seed: int | None = None):
        if not (0.0 <= sample <= 1.0):
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        self.sample = float(sample)
        self.t0 = _now_s()
        self.dropped = 0
        self._lock = threading.Lock()
        self._spans: list[dict] = []
        self._rng = random.Random(seed)

    def sample_decision(self) -> bool:
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        return self._rng.random() < self.sample

    def add(self, span: Span):
        self.add_record(span.to_record())

    def add_record(self, rec: dict):
        """Buffer an already-materialised span record (what the flight
        recorder's tail promotion forwards — byte-for-byte the dict a
        head-sampled span would have contributed)."""
        with self._lock:
            if len(self._spans) >= MAX_SPANS:
                self.dropped += 1
                return
            self._spans.append(rec)

    def spans(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def clear(self):
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    # -- export ------------------------------------------------------------
    def to_chrome(self) -> dict:
        """Chrome trace-event JSON (``ph:"X"`` complete events, µs)."""
        return chrome_doc(self.spans(), self.t0)

    def export_chrome(self, path: str) -> int:
        """Write trace-event JSON; returns the number of span events."""
        doc = self.to_chrome()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return len(doc["traceEvents"]) - 1

    def summary(self, max_roots: int = 5) -> dict:
        """Compact digest for run reports / bench records: root spans
        ranked by duration plus totals."""
        spans = self.spans()
        roots = [s for s in spans if s["parent_id"] is None]
        roots.sort(key=lambda s: -s["dur_s"])
        per_trace: dict[str, int] = {}
        for s in spans:
            per_trace[s["trace_id"]] = per_trace.get(s["trace_id"], 0) + 1
        return {
            "n_spans": len(spans),
            "n_traces": len(per_trace),
            "dropped": self.dropped,
            "roots": [{"name": r["name"], "trace_id": r["trace_id"],
                       "wall_s": round(r["dur_s"], 6),
                       "n_spans": per_trace.get(r["trace_id"], 0)}
                      for r in roots[:max_roots]],
        }


# -- module state ----------------------------------------------------------

_on = False  # THE hot-path guard: one global read when tracing is off
_collector: TraceCollector | None = None
# Installed by obs.recorder.install: routes shadow spans (unsampled
# trees captured at full fidelity) into the flight-recorder ring.
_recorder = None
_current: contextvars.ContextVar = contextvars.ContextVar(
    "heatmap_tpu_span", default=None)


def enable_tracing(sample: float = 1.0,
                   seed: int | None = None) -> TraceCollector:
    """Install a collector and hook the tracer + event log onto the
    tree. Returns the collector (export/summary handle)."""
    global _on, _collector
    _collector = TraceCollector(sample=sample, seed=seed)
    _on = True
    from heatmap_tpu.obs import events, metrics
    from heatmap_tpu.utils import trace

    trace._tree_begin = begin_span
    trace._tree_end = end_span
    events._trace_ids = current_ids
    metrics._exemplar_ids = current_ids
    return _collector


def disable_tracing():
    """Remove the collector and unhook integrations (reset helper)."""
    global _on, _collector
    _on = False
    _collector = None
    from heatmap_tpu.obs import events, metrics
    from heatmap_tpu.utils import trace

    trace._tree_begin = None
    trace._tree_end = None
    events._trace_ids = None
    metrics._exemplar_ids = None


def tracing_enabled() -> bool:
    return _on


def get_collector() -> TraceCollector | None:
    return _collector


def current_span() -> Span | None:
    """The ambient span, or None (off / no root / unsampled root)."""
    if not _on:
        return None
    cur = _current.get()
    return cur if isinstance(cur, Span) else None


def current_ids() -> tuple | None:
    """(trace_id, span_id) of the ambient span — the event-stamping
    hook installed on obs.events."""
    sp = current_span()
    if sp is None:
        return None
    return (sp.trace_id, sp.span_id)


# -- span lifecycle --------------------------------------------------------

def begin_span(name: str, attrs: dict | None = None,
               traceparent: str | None = None):
    """Open a span under the ambient context (root-on-demand).

    Returns a Span, a _NotSampled sentinel (caller must still pass it
    to end_span so the contextvar unwinds), or None when tracing is
    off. ``traceparent`` (only meaningful for roots) continues a remote
    trace and overrides the probabilistic sampling decision with the
    header's sampled flag.
    """
    collector = _collector
    if not _on or collector is None:
        return None
    parent = _current.get()
    if isinstance(parent, _NotSampled):
        if _recorder is None:
            return None  # whole subtree is unsampled; nothing to unwind
        # Flight recorder installed: capture the unsampled subtree at
        # full fidelity as shadow spans (ring-bound, promotable).
        sp = Span(name, parent.trace_id, parent.span_id, attrs)
        sp.shadow = True
    elif parent is None:
        # Root: decide sampling here, once per trace.
        remote = parse_traceparent(traceparent) if traceparent else None
        if remote is not None:
            trace_id, parent_id, sampled = remote
        else:
            trace_id, parent_id = uuid.uuid4().hex, None
            sampled = collector.sample_decision()
        if not sampled:
            if _recorder is None:
                sentinel = _NotSampled(trace_id)
                sentinel._token = _current.set(sentinel)
                return sentinel
            sp = Span(name, trace_id, parent_id, attrs)
            sp.shadow = True
        else:
            sp = Span(name, trace_id, parent_id, attrs)
    else:
        sp = Span(name, parent.trace_id, parent.span_id, attrs)
        sp.shadow = parent.shadow
    sp._token = _current.set(sp)
    return sp


def end_span(sp):
    """Close a span from begin_span: fix duration, unwind the
    contextvar, hand the record to the collector."""
    if sp is None:
        return
    if sp._token is not None:
        _current.reset(sp._token)
        sp._token = None
    if isinstance(sp, _NotSampled):
        return
    sp.dur_s = _now_s() - sp.start_s
    recorder = _recorder
    if sp.shadow:
        # Shadow spans never reach the collector directly; the ring
        # forwards them on tail promotion.
        if recorder is not None:
            recorder.record_span(sp)
        return
    collector = _collector
    if collector is not None:
        collector.add(sp)
    if recorder is not None:
        recorder.record_span(sp)


@contextlib.contextmanager
def span(name: str, traceparent: str | None = None, **attrs):
    """``with tracing.span("serve.request"): ...`` — yields the Span
    (or None when off/unsampled). Roots honor ``traceparent``."""
    sp = begin_span(name, attrs or None, traceparent=traceparent)
    try:
        yield sp if isinstance(sp, Span) else None
    finally:
        end_span(sp)


def context_bound(fn):
    """Bind ``fn`` to the caller's context so the ambient span survives
    into executor worker threads (which otherwise start with an empty
    context). Returns ``fn`` untouched when tracing is off."""
    if not _on:
        return fn
    ctx = contextvars.copy_context()

    def _bound(*args, **kwargs):
        return ctx.run(fn, *args, **kwargs)

    return _bound


# -- traceparent propagation ----------------------------------------------

def current_traceparent() -> str | None:
    """Render the ambient span (sampled or not) as a W3C-style
    ``00-{trace_id}-{span_id}-{flags}`` header, or None."""
    if not _on:
        return None
    cur = _current.get()
    if cur is None:
        return None
    # Shadow spans are real Spans but head-UNSAMPLED: downstream must
    # see flags 00 or remote hops would head-sample the continuation.
    flags = (FLAG_SAMPLED if isinstance(cur, Span) and not cur.shadow
             else 0)
    return (f"{TRACEPARENT_VERSION}-{cur.trace_id}-{cur.span_id}-"
            f"{flags:02x}")


def parse_traceparent(header: str | None):
    """``(trace_id, parent_span_id, sampled)`` or None on malformed
    input (malformed headers start a fresh local trace, never raise)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
        sampled = bool(int(flags, 16) & FLAG_SAMPLED)
    except ValueError:
        return None
    return (trace_id, span_id, sampled)
