"""In-process tiered time-series store: the registry's memory.

Every other obs surface is instantaneous — ``/metrics`` is a scrape,
``snapshot()`` is a point in time, the flight recorder holds spans but
not values. This module gives the process *history* without deploying
an external Prometheus: a background sampler appends registry
snapshots into bounded ring **tiers** (raw ~10 s points rolling up
into 1 m and 10 m buckets on eviction), each bucket carrying
``min/max/sum/count/last`` per series so rates, trends, and "was this
tick normal?" questions are answerable in-process. The same
bounded-error-summary idea the synopsis tier applies spatially
(docs/synopsis.md) applied on the time axis: raw recent samples,
compressed older ones, range queries stamped with the resolution they
were actually answered at.

Design points, mirroring the rest of ``obs/``:

- **Zero-cost when off.** Nothing here is wired into any hot path:
  the sampler *pulls* from the registry on its own thread, so with no
  sampler installed (the default — ``--telemetry-sample-interval 0``)
  the process runs zero extra threads, allocates nothing, and served
  blobs are byte-identical (tests/test_timeseries.py pins both).
- **Deterministic downsample-on-eviction.** When a tier's ring is
  full, the oldest point folds into the next tier's bucket
  (``min=min, max=max, sum+=sum, count+=count, last=newest``) — a
  pure function of the sample stream, so rollups equal brute-force
  recomputation exactly and repeat runs produce identical tiers.
- **Byte-capped.** Rings bound points per series; ``max_bytes`` bounds
  the series population (new series past the cap are dropped and
  counted, never grown).
- **Crash-safe optional spill.** ``spill()`` publishes the store into
  ``<spill_dir>/snap-N`` via the same fsync'd tmp-dir + rename as
  every other artifact (``utils.checkpoint.publish_dir``), keeping one
  previous snapshot; ``load_spill()`` on construction restores the
  newest complete snapshot and quarantines torn ones (a ``.tmp-``
  orphan or an unreadable snap moves to ``quarantine/`` with a
  ``quarantine`` event), so history survives restarts and rides along
  in incident bundles.

The injectable clock (ctor ``clock=time.time``) makes every test
fake-clock deterministic, same as the SLO engine and the incident
manager.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

#: (step_seconds, ring_capacity) finest-first. Raw 10 s x 360 = 1 h,
#: 1 m x 360 = 6 h, 10 m x 432 = 3 days — the retention math in
#: docs/observability.md.
DEFAULT_TIERS = ((10.0, 360), (60.0, 360), (600.0, 432))

#: Conservative in-memory cost of one bucket (7 floats + list
#: overhead); the unit the ``max_bytes`` series cap is computed in.
POINT_BYTES = 120

# Bucket layout: [bucket_ts, min, max, sum, count, last, last_ts].
_TS, _MIN, _MAX, _SUM, _COUNT, _LAST, _LAST_TS = range(7)


def series_key(name: str, labels: dict) -> str:
    """Canonical string key for one (metric, labelset) series:
    ``name`` or ``name{k=v,...}`` with labels sorted."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_series_key(key: str) -> tuple:
    """Inverse of :func:`series_key` -> ``(name, labels_dict)``."""
    name, brace, rest = key.partition("{")
    if not brace:
        return key, {}
    labels = {}
    for pair in rest.rstrip("}").split(","):
        if not pair:
            continue
        k, _, v = pair.partition("=")
        labels[k] = v
    return name, labels


def flatten_snapshot(snapshot: dict) -> dict:
    """Registry snapshot -> ``{series_key: (kind, value)}``.

    Counters and gauges map to their value; a histogram maps to two
    series, ``<name>_sum`` and ``<name>_count`` (buckets are dropped —
    the store keeps trends, not distributions; the live histogram is
    always one ``/metrics`` scrape away).
    """
    flat = {}
    for name, meta in snapshot.items():
        kind = meta.get("type")
        for sample in meta.get("samples", ()):
            labels = sample.get("labels") or {}
            if kind == "histogram":
                flat[series_key(name + "_sum", labels)] = (
                    "counter", float(sample.get("sum", 0.0)))
                flat[series_key(name + "_count", labels)] = (
                    "counter", float(sample.get("count", 0)))
            else:
                try:
                    value = float(sample.get("value", 0.0))
                except (TypeError, ValueError):
                    continue
                flat[series_key(name, labels)] = (kind, value)
    return flat


class TimeSeriesStore:
    """Tiered per-series rings with deterministic rollup-on-eviction."""

    def __init__(self, *, tiers=DEFAULT_TIERS, max_bytes: int = 4 << 20,
                 spill_dir: str | None = None, clock=time.time):
        if not tiers:
            raise ValueError("at least one tier is required")
        steps = [float(s) for s, _ in tiers]
        if steps != sorted(steps):
            raise ValueError("tiers must be ordered finest-first")
        self.tiers = tuple((float(step), int(cap)) for step, cap in tiers)
        self.max_bytes = int(max_bytes)
        self.spill_dir = spill_dir
        self.clock = clock
        worst_case = POINT_BYTES * sum(cap for _, cap in self.tiers)
        self.max_series = max(1, self.max_bytes // worst_case)
        self._lock = threading.Lock()
        # key -> {"kind": str, "tiers": [deque, ...]}
        self._series: dict[str, dict] = {}
        self.samples_total = 0
        self.dropped_series = 0
        self._spill_seq = 0
        if spill_dir:
            self.load_spill()

    # -- append path -------------------------------------------------------

    def append(self, snapshot: dict, ts: float | None = None):
        """Fold one registry snapshot (``MetricsRegistry.snapshot()``)
        into the rings; the sampler's per-tick entry point."""
        self.append_flat(flatten_snapshot(snapshot), ts)

    def append_flat(self, flat: dict, ts: float | None = None):
        when = self.clock() if ts is None else float(ts)
        with self._lock:
            for key in sorted(flat):
                kind, value = flat[key]
                self._observe_locked(key, kind, value, when)
            self.samples_total += 1

    def observe(self, key: str, value: float, ts: float | None = None,
                kind: str = "gauge"):
        """Append one sample of one series (tests, ad-hoc feeds)."""
        when = self.clock() if ts is None else float(ts)
        with self._lock:
            self._observe_locked(key, kind, float(value), when)

    def _observe_locked(self, key, kind, value, when):
        entry = self._series.get(key)
        if entry is None:
            if len(self._series) >= self.max_series:
                self.dropped_series += 1
                return
            entry = {"kind": kind,
                     "tiers": [deque() for _ in self.tiers]}
            self._series[key] = entry
        self._fold(entry["tiers"], 0,
                   [when, value, value, value, 1, value, when])

    def _fold(self, rings, level, point):
        """Merge ``point`` into tier ``level`` at its bucket boundary;
        evictions cascade into the next tier (dropped past the last)."""
        step, cap = self.tiers[level]
        bucket = point[_TS] - (point[_TS] % step)
        ring = rings[level]
        if ring and ring[-1][_TS] == bucket:
            self._merge(ring[-1], point)
            return
        ring.append([bucket, point[_MIN], point[_MAX], point[_SUM],
                     point[_COUNT], point[_LAST], point[_LAST_TS]])
        while len(ring) > cap:
            evicted = ring.popleft()
            if level + 1 < len(self.tiers):
                self._fold(rings, level + 1, evicted)

    @staticmethod
    def _merge(into, point):
        into[_MIN] = min(into[_MIN], point[_MIN])
        into[_MAX] = max(into[_MAX], point[_MAX])
        into[_SUM] += point[_SUM]
        into[_COUNT] += point[_COUNT]
        if point[_LAST_TS] >= into[_LAST_TS]:
            into[_LAST] = point[_LAST]
            into[_LAST_TS] = point[_LAST_TS]

    # -- query path --------------------------------------------------------

    def query(self, name: str, labels: dict | None = None,
              start: float | None = None, end: float | None = None,
              step: float | None = None) -> dict:
        """Range query -> aligned frames stamped with the resolution
        they were answered at.

        ``name`` matches the metric name exactly; ``labels`` (subset
        match) narrows the label sets. ``start``/``end`` default to the
        last hour; ``step`` asks for a coarser resolution (buckets are
        regrouped deterministically — the achieved step is always
        stamped, never assumed). Tier choice per series: the finest
        tier whose retention still covers ``start``, falling back to
        the coarsest.
        """
        end_ts = self.clock() if end is None else float(end)
        start_ts = end_ts - 3600.0 if start is None else float(start)
        want = labels or {}
        frames = []
        with self._lock:
            for key in sorted(self._series):
                k_name, k_labels = parse_series_key(key)
                if k_name != name:
                    continue
                if any(k_labels.get(lk) != lv for lk, lv in want.items()):
                    continue
                entry = self._series[key]
                frame = self._frame_locked(entry, start_ts, end_ts, step)
                if frame is not None:
                    frame["labels"] = k_labels
                    frame["key"] = key
                    frames.append(frame)
        return {"name": name, "from": start_ts, "to": end_ts,
                "requested_step": step, "frames": frames}

    def _frame_locked(self, entry, start_ts, end_ts, step):
        chosen, chosen_step = None, None
        for level, (tier_step, _cap) in enumerate(self.tiers):
            ring = entry["tiers"][level]
            if ring and ring[0][_TS] <= start_ts:
                chosen, chosen_step = level, tier_step
                break
        if chosen is None:  # nothing retains back to start: coarsest
            for level in range(len(self.tiers) - 1, -1, -1):
                if entry["tiers"][level]:
                    chosen, chosen_step = level, self.tiers[level][0]
                    break
        if chosen is None:
            return None
        points = [list(p) for p in entry["tiers"][chosen]
                  if start_ts <= p[_TS] + chosen_step and p[_TS] < end_ts]
        achieved = chosen_step
        if step is not None and float(step) > chosen_step:
            achieved = float(step)
            regrouped: dict = {}
            order = []
            for p in points:
                bucket = p[_TS] - (p[_TS] % achieved)
                have = regrouped.get(bucket)
                if have is None:
                    have = [bucket, p[_MIN], p[_MAX], p[_SUM],
                            p[_COUNT], p[_LAST], p[_LAST_TS]]
                    regrouped[bucket] = have
                    order.append(bucket)
                else:
                    self._merge(have, p)
            points = [regrouped[b] for b in order]
        return {"step": achieved, "tier": chosen,
                "points": [p[:_LAST + 1] for p in points]}

    # -- snapshots (incident bundles, dashboard, spill) --------------------

    def recent_window(self, seconds: float = 300.0,
                      max_series: int = 64) -> dict:
        """The raw-tier window of the last ``seconds`` per series —
        what an incident bundle embeds so a post-mortem can read what
        changed just before the trigger."""
        now = self.clock()
        cut = now - float(seconds)
        out, truncated = {}, 0
        with self._lock:
            for key in sorted(self._series):
                points = [p[:_LAST + 1] for p in self._series[key]["tiers"][0]
                          if p[_TS] >= cut]
                if not points:
                    continue
                if len(out) >= max_series:
                    truncated += 1
                    continue
                out[key] = {"step": self.tiers[0][0], "points": points}
        return {"from": cut, "to": now, "window_s": float(seconds),
                "truncated_series": truncated, "series": out}

    def series_names(self) -> list:
        with self._lock:
            return sorted(self._series)

    def stats(self) -> dict:
        with self._lock:
            points = sum(len(ring) for e in self._series.values()
                         for ring in e["tiers"])
            return {
                "series": len(self._series),
                "points": points,
                "samples_total": self.samples_total,
                "dropped_series": self.dropped_series,
                "max_series": self.max_series,
                "tiers": [{"step_s": step, "capacity": cap}
                          for step, cap in self.tiers],
                "approx_bytes": points * POINT_BYTES,
                "spill_dir": self.spill_dir,
            }

    # -- crash-safe spill --------------------------------------------------

    def _dump_locked(self) -> dict:
        return {
            "version": 1,
            "tiers": [[step, cap] for step, cap in self.tiers],
            "samples_total": self.samples_total,
            "series": {key: {"kind": e["kind"],
                             "tiers": [[list(p) for p in ring]
                                       for ring in e["tiers"]]}
                       for key, e in self._series.items()},
        }

    def spill(self) -> str | None:
        """Publish the store under ``spill_dir`` atomically (tmp dir +
        fsync + rename, the ``publish_dir`` contract) and prune all but
        the previous snapshot. No-op without a spill dir."""
        if not self.spill_dir:
            return None
        from heatmap_tpu.utils.checkpoint import publish_dir

        with self._lock:
            doc = self._dump_locked()
        os.makedirs(self.spill_dir, exist_ok=True)
        existing = [int(d.split("-", 1)[1]) for d in os.listdir(self.spill_dir)
                    if d.startswith("snap-") and d.split("-", 1)[1].isdigit()]
        seq = max([self._spill_seq - 1] + existing) + 1
        self._spill_seq = seq + 1
        final = os.path.join(self.spill_dir, f"snap-{seq:06d}")
        tmp = os.path.join(self.spill_dir, f".tmp-snap-{seq:06d}")
        os.makedirs(tmp, exist_ok=True)
        payload = json.dumps(doc, sort_keys=True).encode()
        with open(os.path.join(tmp, "series.json"), "wb") as f:
            f.write(payload)
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump({"version": 1, "seq": seq, "bytes": len(payload),
                       "series": len(doc["series"])}, f, sort_keys=True)
        publish_dir(tmp, final)
        for old in sorted(existing):
            if old < seq - 1:
                _rmtree(os.path.join(self.spill_dir, f"snap-{old:06d}"))
        return final

    def load_spill(self) -> str | None:
        """Restore the newest complete snapshot under ``spill_dir``;
        torn entries (``.tmp-`` orphans, unreadable/malformed snaps)
        are quarantined, never trusted."""
        if not self.spill_dir or not os.path.isdir(self.spill_dir):
            return None
        names = sorted(os.listdir(self.spill_dir))
        for name in names:
            if name.startswith(".tmp-"):
                self._quarantine(name, "orphan_tmp")
        snaps = sorted((n for n in os.listdir(self.spill_dir)
                        if n.startswith("snap-")), reverse=True)
        for name in snaps:
            path = os.path.join(self.spill_dir, name)
            doc = self._read_snap(path)
            if doc is None:
                self._quarantine(name, "torn_telemetry")
                continue
            with self._lock:
                self._series = {
                    key: {"kind": e.get("kind", "gauge"),
                          "tiers": [deque(list(p) for p in ring)
                                    for ring in e["tiers"]]}
                    for key, e in doc.get("series", {}).items()
                    if len(e.get("tiers", ())) == len(self.tiers)}
                self.samples_total = int(doc.get("samples_total", 0))
                self._spill_seq = int(name.split("-", 1)[1]) + 1
            return path
        return None

    def _read_snap(self, path: str):
        try:
            with open(os.path.join(path, "manifest.json")) as f:
                manifest = json.load(f)
            with open(os.path.join(path, "series.json"), "rb") as f:
                payload = f.read()
            if manifest.get("bytes") != len(payload):
                return None
            doc = json.loads(payload)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None

    def _quarantine(self, name: str, reason: str):
        from heatmap_tpu.obs import events

        src = os.path.join(self.spill_dir, name)
        qdir = os.path.join(self.spill_dir, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, name.lstrip("."))
        try:
            if os.path.exists(dst):
                _rmtree(dst)
            os.rename(src, dst)
        except OSError:
            return
        events.emit("quarantine", root=self.spill_dir, path=dst,
                    reason=reason, kind="telemetry")


def _rmtree(path: str):
    import shutil

    shutil.rmtree(path, ignore_errors=True)


class TelemetrySampler:
    """Background sampler: one registry snapshot into the store per
    ``interval_s``, feeding the anomaly engine on the same tick.

    The thread waits on a :class:`threading.Event` (never sleeps) so
    ``stop()`` returns promptly; ``sample_once()`` is the same tick
    the thread runs, callable directly under a fake clock for
    deterministic tests. A sampling failure is swallowed and counted —
    telemetry must never take the process down.
    """

    def __init__(self, store: TimeSeriesStore, interval_s: float, *,
                 registry=None, engine=None, clock=time.time,
                 spill_every_ticks: int = 6):
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        self.store = store
        self.interval_s = float(interval_s)
        self.engine = engine
        self.clock = clock
        self.spill_every_ticks = int(spill_every_ticks)
        self._registry = registry
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.ticks = 0
        self.errors = 0

    def sample_once(self, ts: float | None = None):
        from heatmap_tpu.obs import metrics

        registry = self._registry or metrics.get_registry()
        when = self.clock() if ts is None else float(ts)
        flat = flatten_snapshot(registry.snapshot())
        self.store.append_flat(flat, when)
        self.ticks += 1
        engine = self.engine
        if engine is not None:
            engine.observe_tick(flat, when)
        if (self.store.spill_dir and self.spill_every_ticks > 0
                and self.ticks % self.spill_every_ticks == 0):
            self.store.spill()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                self.errors += 1

    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="telemetry-sampler",
                                        daemon=True)
        self._thread.start()

    def stop(self, spill: bool = True):
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None
        if spill and self.store.spill_dir:
            try:
                self.store.spill()
            except OSError:
                pass


# -- module state (the obs install/get house pattern) -----------------------

_store: TimeSeriesStore | None = None
_sampler: TelemetrySampler | None = None


def install(store: TimeSeriesStore | None):
    """Install (or clear, with None) the process-wide store read by
    ``/series``, ``/dashboard``, and incident-bundle embedding."""
    global _store
    _store = store


def get_store() -> TimeSeriesStore | None:
    return _store


def get_sampler() -> TelemetrySampler | None:
    return _sampler


def arm(interval_s: float, *, engine=None, spill_dir: str | None = None,
        tiers=DEFAULT_TIERS, max_bytes: int = 4 << 20,
        clock=time.time) -> TelemetrySampler:
    """Construct + install a store and start its sampler thread — the
    CLI's one-call arming (``--telemetry-sample-interval``)."""
    global _sampler
    store = TimeSeriesStore(tiers=tiers, max_bytes=max_bytes,
                            spill_dir=spill_dir, clock=clock)
    install(store)
    sampler = TelemetrySampler(store, interval_s, engine=engine,
                               clock=clock)
    _sampler = sampler
    sampler.start()
    return sampler


def flush_spill():
    """Best-effort spill of the installed store (shutdown paths; no-op
    when nothing is installed or no spill dir is configured)."""
    store = _store
    if store is not None and store.spill_dir:
        try:
            store.spill()
        except OSError:
            pass


def shutdown():
    """Stop the sampler thread (spilling once) and clear the installed
    store — the teardown conftest runs between tests."""
    global _sampler, _store
    sampler = _sampler
    _sampler = None
    if sampler is not None:
        sampler.stop()
    _store = None
