"""Incident bundles: flush the flight recorder on failure edges.

A **trigger** — SLO breach rising edge, typed-503 shed, injected-fault
storm over a rate threshold, degraded-enter, a telemetry anomaly edge
(obs/anomaly.py), or an uncaught exception in a CLI job — flushes one
self-contained bundle under ``<incident_dir>/<run_id>-<seq>/``:

- ``trace.json``    ring spans as Perfetto/Chrome trace-event JSON
  (loadable in chrome://tracing and by tools/trace_analyze.py);
- ``events.json``   the recent event tail from the ring;
- ``metrics.json``  full registry snapshot (exemplars included);
- ``telemetry.json`` the raw-tier time-series window preceding the
  trigger (when a telemetry store is installed) — the "what changed
  in the last 5 minutes" a point-in-time snapshot cannot answer;
- ``state.json``    whatever state providers are registered —
  /healthz + breaker/fleet state from serve, config fingerprint and
  delta/synopsis epochs from the CLI;
- ``manifest.json`` envelope: trigger, detail, run_id/seq, per-file
  bytes, recorder stats.

Bundles are **atomic** (written to a dot-tmp sibling then renamed),
**rate-limited** per trigger kind (``min_interval_s`` on an injectable
clock so tests and chaos_soak pin exact bundle counts), **size-capped**
(event/span tails are trimmed oldest-first until the serialized bundle
fits ``max_bytes``), and **pruned** with the same age-wins retention
discipline as delta/recover.py quarantine: keep the newest ``keep``
bundles, but never delete one younger than ``min_age_s`` — age wins
over count, so a burst cannot evict the bundle you are reading.

Module-level state mirrors the event-log pattern: ``set_manager``
installs the process-wide manager and wires it as the recorder's
event hook; :func:`trigger` no-ops when none is installed.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import uuid
from collections import deque

DEFAULT_KEEP = 16
DEFAULT_MIN_AGE_S = 300.0
DEFAULT_MIN_INTERVAL_S = 30.0
DEFAULT_MAX_BYTES = 4_000_000
DEFAULT_EVENT_TAIL = 400
DEFAULT_STORM_THRESHOLD = 8
DEFAULT_STORM_WINDOW_S = 10.0

TRIGGER_KINDS = ("slo_breach", "shed", "fault_storm", "degraded_enter",
                 "anomaly", "exception")
DEFAULT_TELEMETRY_WINDOW_S = 300.0


class IncidentManager:
    """Owns the incident directory: trigger edges in, bundles out."""

    def __init__(self, out_dir: str, *, run_id: str | None = None,
                 keep: int = DEFAULT_KEEP,
                 min_age_s: float = DEFAULT_MIN_AGE_S,
                 min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 event_tail: int = DEFAULT_EVENT_TAIL,
                 storm_threshold: int = DEFAULT_STORM_THRESHOLD,
                 storm_window_s: float = DEFAULT_STORM_WINDOW_S,
                 clock=time.time):
        self.out_dir = out_dir
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self.keep = int(keep)
        self.min_age_s = float(min_age_s)
        self.min_interval_s = float(min_interval_s)
        self.max_bytes = int(max_bytes)
        self.event_tail = int(event_tail)
        self.storm_threshold = int(storm_threshold)
        self.storm_window_s = float(storm_window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        self._last_flush: dict[str, float] = {}
        self._fault_ts: deque = deque(maxlen=1024)
        self._providers: dict = {}
        self.flushed: list[str] = []
        self.suppressed = 0
        os.makedirs(out_dir, exist_ok=True)

    # -- state providers ---------------------------------------------------
    def add_state_provider(self, name: str, fn):
        """Register a callable folded into the bundle's state.json
        (serve /healthz, fleet breakers, config fingerprint...)."""
        with self._lock:
            self._providers[str(name)] = fn

    # -- trigger detection over the event stream ---------------------------
    def on_event(self, rec: dict):
        """Recorder event hook: turn failure-edge events into flushes.
        slo_breach and degraded_enter are already edge-triggered at
        their source (one record per episode); fault storms are
        detected here over the events' own wall-clock timestamps so a
        seeded chaos replay detects the same storms."""
        event = rec.get("event")
        if event == "slo_breach":
            self.trigger("slo_breach", detail=rec.get("slo"))
        elif event == "degraded_enter":
            self.trigger("degraded_enter", detail=rec.get("cause"))
        elif event == "anomaly_detected":
            self.trigger("anomaly", detail=rec.get("series"))
        elif event == "fault_injected":
            ts = rec.get("ts", 0.0)
            storm = False
            with self._lock:
                self._fault_ts.append(ts)
                window = [t for t in self._fault_ts
                          if ts - t <= self.storm_window_s]
                if len(window) >= self.storm_threshold:
                    storm = True
                    self._fault_ts.clear()  # next episode starts fresh
            if storm:
                self.trigger("fault_storm", detail=rec.get("site"))

    # -- flushing ----------------------------------------------------------
    def trigger(self, kind: str, detail=None) -> str | None:
        """Flush one bundle for a trigger edge; returns its path, or
        None when the per-kind rate limit suppressed it."""
        now = self._clock()
        with self._lock:
            last = self._last_flush.get(kind)
            if last is not None and (now - last) < self.min_interval_s:
                self.suppressed += 1
                return None
            self._last_flush[kind] = now
            seq = self._seq
            self._seq += 1
        path = self._flush(kind, detail, seq, now)
        from heatmap_tpu.obs import INCIDENTS_TOTAL, events

        INCIDENTS_TOTAL.inc(trigger=kind)
        events.emit("incident_flush", trigger=kind, path=path,
                    seq=seq, detail=None if detail is None else str(detail))
        return path

    def _flush(self, kind: str, detail, seq: int, now: float) -> str:
        from heatmap_tpu.obs import recorder as recorder_mod
        from heatmap_tpu.obs import metrics, tracing

        rcd = recorder_mod.get_recorder()
        spans = rcd.span_records() if rcd is not None else []
        tail = (rcd.event_records() if rcd is not None else [])
        tail = tail[-self.event_tail:]
        collector = tracing.get_collector()
        if collector is not None:
            t0 = collector.t0
        else:
            t0 = min((s["start_s"] for s in spans), default=0.0)
        with self._lock:
            providers = dict(self._providers)
        state = {}
        for name, fn in sorted(providers.items()):
            try:
                state[name] = fn()
            except Exception as e:  # a dying subsystem must not block
                state[name] = {"error": repr(e)}

        # Recent telemetry history (obs/timeseries.py): the raw-tier
        # window preceding the trigger, so the bundle answers "what
        # changed in the 5 minutes before this fired" — not just the
        # instantaneous metrics.json snapshot. Bounded by the store's
        # own rings, so it rides outside the trim loop.
        from heatmap_tpu.obs import timeseries

        ts_store = timeseries.get_store()
        telemetry = (ts_store.recent_window(DEFAULT_TELEMETRY_WINDOW_S)
                     if ts_store is not None else None)

        # Size cap: trim the tails oldest-first until the bundle fits.
        files = None
        while True:
            files = {
                "trace.json": json.dumps(
                    tracing.chrome_doc(spans, t0), default=str),
                "events.json": json.dumps(tail, default=str),
                "metrics.json": json.dumps(
                    metrics.get_registry().snapshot(), indent=1,
                    sort_keys=True, default=str),
                "state.json": json.dumps(state, indent=1, sort_keys=True,
                                         default=str),
            }
            if telemetry is not None:
                files["telemetry.json"] = json.dumps(
                    telemetry, sort_keys=True, default=str)
            total = sum(len(v) for v in files.values())
            if total <= self.max_bytes or (not spans and not tail):
                break
            if len(tail) >= len(spans):
                tail = tail[len(tail) // 2 + 1:]
            else:
                spans = spans[len(spans) // 2 + 1:]

        manifest = {
            "run_id": self.run_id, "seq": seq, "trigger": kind,
            "detail": None if detail is None else str(detail),
            "ts": now, "bytes": total,
            "files": {name: len(body) for name, body in files.items()},
            "recorder": rcd.stats() if rcd is not None else None,
            "trace_dropped": (collector.dropped if collector is not None
                              else None),
        }
        files["manifest.json"] = json.dumps(manifest, indent=1,
                                            sort_keys=True, default=str)

        name = f"{self.run_id}-{seq}"
        tmp = os.path.join(self.out_dir, f".tmp-{name}")
        final = os.path.join(self.out_dir, name)
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        for fname, body in files.items():
            with open(os.path.join(tmp, fname), "w") as f:
                f.write(body)
        shutil.rmtree(final, ignore_errors=True)
        os.rename(tmp, final)
        self.flushed.append(final)
        self.prune(now=now)
        return final

    # -- retention ---------------------------------------------------------
    def prune(self, now: float | None = None) -> dict:
        """Age-wins retention (the delta/recover.py quarantine
        discipline): keep the newest ``keep`` bundles; beyond that,
        delete — unless the bundle is younger than ``min_age_s``."""
        if now is None:
            now = self._clock()
        entries = []
        try:
            names = os.listdir(self.out_dir)
        except OSError:
            return {"pruned": 0, "kept": 0}
        for name in names:
            full = os.path.join(self.out_dir, name)
            if name.startswith(".tmp-") or not os.path.isdir(full):
                continue
            try:
                mtime = os.path.getmtime(full)
            except OSError:
                continue
            entries.append((mtime, name, full))
        entries.sort(reverse=True)  # newest first
        pruned = 0
        for mtime, _name, full in entries[self.keep:]:
            if (now - mtime) < self.min_age_s:
                continue  # age wins over count
            shutil.rmtree(full, ignore_errors=True)
            pruned += 1
        return {"pruned": pruned, "kept": len(entries) - pruned}


# -- process-wide default manager -------------------------------------------

_manager: IncidentManager | None = None


def set_manager(manager: IncidentManager | None):
    """Install (or clear) the default manager and wire it into the
    recorder's event dispatch so failure-edge events reach it."""
    global _manager
    _manager = manager
    from heatmap_tpu.obs import recorder as recorder_mod

    recorder_mod._incident_hook = (manager.on_event
                                   if manager is not None else None)
    recorder_mod._sync_hooks()


def get_manager() -> IncidentManager | None:
    return _manager


def trigger(kind: str, detail=None) -> str | None:
    """Flush on the default manager; no-op (None) when none installed."""
    manager = _manager
    if manager is None:
        return None
    return manager.trigger(kind, detail=detail)


def add_state_provider(name: str, fn):
    """Register a provider on the default manager (no-op when none)."""
    manager = _manager
    if manager is not None:
        manager.add_state_provider(name, fn)
