"""Structured run events: append-only JSONL with a checked-in schema.

Every record carries the envelope ``{run_id, seq, ts, event}`` — ``seq``
is monotonic per log (assigned under the writer lock, so concurrent
producer threads cannot collide) and ``ts`` is Unix wall-clock. The
payload fields allowed per event type are pinned in ``EVENT_SCHEMA``;
``validate_event`` rejects unknown fields and missing required ones, so
the log a run emits is exactly the catalog docs/observability.md
documents — an instrumentation site cannot invent an ad-hoc field
without also widening the schema (and its tests).

Emission is a module-level ``emit(event, **fields)`` that no-ops when no
log is installed (``set_event_log``), mirroring the zero-cost stance of
the metrics registry: hot paths pay one global read when events are off.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

# event -> {"required": (...), "optional": (...)}. The envelope fields
# (run_id/seq/ts/event) are implicit on every record.
EVENT_SCHEMA = {
    # Job manifest: resolved config, CLI backend, device topology.
    "run_start": {"required": ("config", "backend", "devices"),
                  "optional": ("argv",)},
    # One per closed tracer span when an event log is installed.
    # trace_id/span_id land automatically when tracing is on (the span
    # that just closed), linking slow aggregates back to span trees.
    "stage_end": {"required": ("stage", "wall_s"),
                  "optional": ("items", "bytes", "backend", "level",
                               "window", "trace_id", "span_id")},
    # Job-level routing decision: how cascade_backend="auto" resolved.
    # ``dispatch`` records how the mesh formulation resolved ("gspmd"
    # one-program NamedSharding vs "shard_map" oracle — pipeline/batch
    # resolved_dispatch), so dispatcher routing stays auditable.
    "backend_resolved": {"required": ("requested", "resolved"),
                         "optional": ("reason", "weighted", "data_parallel",
                                      "n_emissions", "spatial_partition",
                                      "dispatch")},
    # Per-call cascade dispatch record (the audit trail behind
    # backend_resolved: what run_cascade actually executed).
    "cascade_dispatch": {"required": ("backend",),
                         "optional": ("jit", "mesh", "merge", "n_emissions",
                                      "n_slots", "trace_id", "span_id",
                                      "partition", "dispatch")},
    # Morton-range partition plan for a cascade dispatch
    # (parallel/partition.plan_partition): the split codes, the sampled
    # evidence they were chosen from, and the post-resplit balance.
    "partition_planned": {"required": ("n_shards", "splits",
                                       "sampled_points", "balance_factor",
                                       "max_shard_mass", "mean_shard_mass"),
                          "optional": ("skew_ratio", "resplits", "degenerate",
                                       "fingerprint", "boundary_tiles")},
    # jax.local_devices()[i].memory_stats() snapshot (empty on CPU).
    "device_memory": {"required": ("samples",), "optional": ()},
    # utils/recovery.py shard retry loop.
    "retry": {"required": ("shard", "attempt", "error"), "optional": ()},
    "recovery": {"required": ("shard", "attempts"), "optional": ()},
    # parallel/multihost.py per-host phase heartbeats.
    # traceparent (W3C-style 00-{trace_id}-{span_id}-{flags}) carries
    # the emitting host's ambient trace across process boundaries.
    "heartbeat": {"required": ("process_index", "process_count", "phase"),
                  "optional": ("uptime_s", "traceparent")},
    # utils/trace.py jax_profile failed to start (satellite fix).
    "profiler_unavailable": {"required": ("error",), "optional": ("logdir",)},
    # serve/http.py per-request record (route is the coarse family,
    # e.g. "tiles"; path the concrete URL; cache "hit"/"miss" on tiles).
    "http_request": {"required": ("route", "status"),
                     "optional": ("path", "ms", "bytes", "cache",
                                  "trace_id", "span_id")},
    # serve/store.py full index rebuild (TileStore.reload): every
    # cached tile is invalidated by the generation bump — the
    # heavyweight counterpart to a targeted delta apply.
    "store_reload": {"required": ("old_generation", "generation",
                                  "levels", "seconds"),
                     "optional": ("spec", "layers", "initial")},
    # delta/: one journaled batch applied (sign -1 = retraction).
    # duplicate=True means the content hash was already journaled and
    # the apply was an idempotent no-op (epoch is the existing one).
    "delta_applied": {"required": ("epoch", "points", "sign", "seconds"),
                      "optional": ("content_hash", "artifact", "rows",
                                   "duplicate", "watermark",
                                   "keys_invalidated")},
    # ingest/: one continuous-ingest tick — one micro-batch journaled,
    # applied, and published (delta_applied covers the apply inside;
    # this record adds the loop's view: event-time watermark, queue
    # depth at dequeue, and end-to-end ingest->servable lag).
    "ingest_tick": {"required": ("tick", "points", "seconds"),
                    "optional": ("epoch", "duplicate", "watermark",
                                 "lag_s", "queue_depth", "keys_invalidated",
                                 "compacted", "trace_id", "span_id")},
    # delta/compact.py: fold the live delta stack into a new base.
    "compaction_start": {"required": ("root", "deltas"),
                         "optional": ("base",)},
    "compaction_end": {"required": ("root", "seconds", "status"),
                       "optional": ("base", "levels", "rows",
                                    "pruned_entries", "error", "buckets")},
    # delta/retract.py: one predicate retraction completed — journal
    # scanned, exact signed counter-batches applied per epoch bucket.
    # rows counts retracted source points, batches the counter-batches
    # (one per surviving (bucket, column-signature) group).
    "retraction_applied": {"required": ("root", "rows", "batches"),
                           "optional": ("scanned", "where", "epochs",
                                        "seconds")},
    # serve/http.py: a tile answered from a temporal fold (?as_of=,
    # ?window=, ?decay= — mode names which). Raw request params ride
    # along so traffic replay can rebuild the fold population.
    "temporal_served": {"required": ("layer", "zoom", "mode"),
                        "optional": ("as_of", "window", "decay",
                                     "cache", "ms")},
    # ingest/loop.py: the newest bucket edge advanced past a window
    # boundary — exactly the retiring bucket's tile keys (x their
    # served window variants) were invalidated; everything else stays.
    "bucket_roll": {"required": ("root", "prev_ref", "ref"),
                    "optional": ("retired", "keys_invalidated",
                                 "windows")},
    # faults/: one record per injected fault. ``seq`` is the plane's own
    # monotonic injection counter (not the envelope seq), so a chaos run
    # can be replayed check-for-check from its event log.
    "fault_injected": {"required": ("site", "fault_seq"),
                       "optional": ("key", "rule", "trace_id", "span_id")},
    # serve/http.py degraded-mode transitions (/healthz mirrors the
    # active cause set). Emitted on cause-set edges, not per request.
    "degraded_enter": {"required": ("cause",), "optional": ("detail",)},
    "degraded_exit": {"required": ("cause",), "optional": ("detail",)},
    # serve/degrade.py brownout ladder: one record per rung transition
    # (edge-triggered — never per request). ``cause`` is the hottest
    # objective on the way up, "recovery" on the way down; ``burn`` the
    # max burn fraction that drove the step.
    "degrade_step": {"required": ("rung", "direction", "cause", "burn"),
                     "optional": ("from_rung", "detail")},
    # delta/recover.py startup sweep: one per quarantined artifact
    # (orphan *.tmp, torn/hash-mismatched journal entry, unjournaled
    # delta dir, stale base dir).
    "quarantine": {"required": ("root", "path", "reason"),
                   "optional": ("kind", "detail")},
    # parallel/elastic.py: the elastic coordinator's lineage decisions.
    # shard_orphaned marks a stale host's unfinished shard (one record
    # per shard, paired 1:1 with the shard_reassigned that names the
    # surviving winner); speculative_launch is a duplicate execution of
    # a straggling shard, and speculative_win fires only when the
    # duplicate beats the original (the loser's artifact is quarantined,
    # never merged).
    "shard_orphaned": {"required": ("shard", "host"),
                       "optional": ("reason",)},
    "shard_reassigned": {"required": ("shard", "from_host", "to_host"),
                         "optional": ()},
    "speculative_launch": {"required": ("shard", "host"),
                           "optional": ("runtime_s", "threshold_s")},
    "speculative_win": {"required": ("shard", "winner"),
                        "optional": ("loser", "quarantined")},
    # serve/router.py fleet membership edges: a backend's circuit
    # breaker opening (crash, probe failures, reload failure) emits
    # _down once per episode; the half-open probe that re-closes it
    # emits _up. Edge-triggered like degraded_enter/exit — one pair
    # per outage, not one per failed request.
    "fleet_backend_down": {"required": ("backend", "reason"),
                           "optional": ("detail",)},
    "fleet_backend_up": {"required": ("backend",),
                         "optional": ("detail",)},
    # obs/slo.py: an objective's burn rate crossed 1.0 (rising edge;
    # one record per breach episode, not per evaluation).
    "slo_breach": {"required": ("slo", "burn_rate"),
                   "optional": ("kind", "compliance", "target",
                                "window_s", "detail")},
    # synopsis/build.py: one wavelet-synopsis artifact published for a
    # coarse level (egress, compaction rebuild, or the ingest loop's
    # provisional early-serve build). max_err is the stamped L-inf
    # bound (the ACHIEVED worst cell error across pairs).
    "synopsis_built": {"required": ("zoom", "pairs", "bytes", "max_err"),
                       "optional": ("coefficients", "path", "provisional")},
    # serve/http.py: a tile was answered from a decoded synopsis
    # (?synopsis=1 or layer policy). stale=True marks a provisional
    # early-serve overlay not yet superseded by the exact apply.
    "synopsis_served": {"required": ("layer", "zoom", "max_err"),
                        "optional": ("stale", "source_zoom", "stretched")},
    # analytics/integral.py: one summed-area (integral) artifact
    # published for a coarse level (egress or compaction rebuild).
    "integral_built": {"required": ("zoom", "pairs", "bytes"),
                       "optional": ("path",)},
    # serve/http.py: one /query answered. path names the evaluator:
    # integral (SAT corner lookups / pruned descent), fallback (exact
    # row scan, pre-integral store), synopsis (brownout grid, with the
    # propagated error bound in max_err).
    "query_served": {"required": ("op", "zoom", "path"),
                     "optional": ("layer", "bbox_area", "cells", "k",
                                  "q", "max_err", "ms", "window",
                                  "slots")},
    # obs/anomaly.py: a watched series' EWMA+MAD z-score crossed its
    # threshold (rising edge; one record per breach episode, cleared
    # with hysteresis — never per sampler tick). series is the
    # flattened telemetry key, watch the spec name that matched.
    "anomaly_detected": {"required": ("series", "z"),
                         "optional": ("threshold", "watch", "value",
                                      "detail")},
    # obs/incident.py: one incident bundle flushed (trigger is the
    # edge kind — slo_breach | shed | fault_storm | degraded_enter |
    # anomaly | exception; path the bundle directory; seq the
    # manager's own monotonic bundle counter).
    "incident_flush": {"required": ("trigger", "path"),
                       "optional": ("seq", "detail", "bytes")},
    # tilefs/prewarm.py: one cache pre-warm pass finished (startup or
    # post-/reload). keys counts 2xx replays; planned the full plan
    # length; budget_exhausted marks a time/byte budget cutoff before
    # the plan drained.
    "prewarm_done": {"required": ("keys", "seconds"),
                     "optional": ("bytes", "errors", "planned",
                                  "budget_exhausted", "source")},
    # writeplane/plane.py: one full batch routed across Morton ranges
    # (ranges = sub-applies routed; 0 with duplicate=True means the
    # full-batch ledger deduped it before routing).
    "writeplane_append": {"required": ("points", "ranges"),
                          "optional": ("sign", "duplicate", "seconds",
                                       "content_hash")},
    # writeplane/manifest.py epoch flip: the cross-range visibility
    # point (live_deltas = journal entries not yet compacted, summed
    # over ranges — the reader-side merge width).
    "writeplane_publish": {"required": ("epoch", "ranges"),
                          "optional": ("seconds", "live_deltas")},
    # writeplane/plane.py hot-range re-split: journal handoff + a new
    # range owning [split, hi) — one record per rebalance.
    "writeplane_rebalance": {"required": ("range", "new_range", "split"),
                             "optional": ("reason", "seconds")},
    # Terminal record: exit status + output fingerprint.
    "run_end": {"required": ("status",),
                "optional": ("blobs", "rows", "levels", "checksum",
                             "seconds", "error")},
}

ENVELOPE_FIELDS = ("run_id", "seq", "ts", "event")


def validate_event(rec: dict):
    """Raise ValueError unless ``rec`` is a well-formed event record."""
    if not isinstance(rec, dict):
        raise ValueError(f"event record must be a dict, got {type(rec)}")
    for field in ENVELOPE_FIELDS:
        if field not in rec:
            raise ValueError(f"event record missing envelope field {field!r}")
    if not isinstance(rec["run_id"], str) or not rec["run_id"]:
        raise ValueError("run_id must be a non-empty string")
    if not isinstance(rec["seq"], int) or rec["seq"] < 0:
        raise ValueError("seq must be a non-negative integer")
    if not isinstance(rec["ts"], (int, float)):
        raise ValueError("ts must be numeric")
    event = rec["event"]
    spec = EVENT_SCHEMA.get(event)
    if spec is None:
        raise ValueError(f"unknown event type {event!r}")
    payload = {k for k in rec if k not in ENVELOPE_FIELDS}
    missing = set(spec["required"]) - payload
    if missing:
        raise ValueError(f"{event}: missing required field(s) "
                         f"{sorted(missing)}")
    unknown = payload - set(spec["required"]) - set(spec["optional"])
    if unknown:
        raise ValueError(f"{event}: unknown field(s) {sorted(unknown)}")


class EventLog:
    """Append-only JSONL writer with per-run id and monotonic seq.

    Lines are flushed as written so a crash loses at most the record in
    flight; ``seq`` gaps in a recovered log therefore mean lost tail,
    never reordering.
    """

    def __init__(self, path: str, run_id: str | None = None):
        self.path = path
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self._lock = threading.Lock()
        self._seq = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "a")

    def emit(self, event: str, **fields) -> dict:
        rec = {"run_id": self.run_id, "seq": 0, "ts": time.time(),
               "event": event, **fields}
        with self._lock:
            if self._fh is None:
                raise ValueError(f"event log {self.path} is closed")
            rec["seq"] = self._seq
            validate_event(rec)
            self._seq += 1
            self._fh.write(json.dumps(rec, sort_keys=False,
                                      default=str) + "\n")
            self._fh.flush()
        return rec

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


_current: EventLog | None = None

# Integration hooks, both None unless their owner installed them (one
# global read each on the emit path, keeping the zero-cost stance):
# - _trace_ids: set by obs.tracing.enable_tracing; returns the ambient
#   (trace_id, span_id) so _TRACE_STAMPED events link to span trees.
# - _observer: set by obs.slo.set_engine; sees every emitted record so
#   the SLO window fills without re-reading the log file.
# - _recorder: set by obs.recorder when a flight recorder or incident
#   manager is installed; sees every record (ring tail + trigger
#   detection), even without a log or observer.
_trace_ids = None
_observer = None
_recorder = None

# Events that get the ambient trace identity stamped automatically
# (explicit trace_id in fields always wins, e.g. serve passes the
# request root's ids after the span has closed).
_TRACE_STAMPED = frozenset(
    {"stage_end", "http_request", "fault_injected", "cascade_dispatch",
     "ingest_tick"})


def set_event_log(log: EventLog | None):
    """Install (or clear, with None) the process-wide event log."""
    global _current
    _current = log


def get_event_log() -> EventLog | None:
    return _current


def emit(event: str, **fields) -> dict | None:
    """Emit to the installed log; no-op (returns None) when none is set.

    The observer hook fires even without a log (on a synthetic,
    unjournaled record), so ``serve --slo`` fills its compliance
    window without requiring ``--events``.
    """
    log = _current
    observer = _observer
    recorder = _recorder
    if log is None and observer is None and recorder is None:
        return None
    ids_fn = _trace_ids
    if (ids_fn is not None and event in _TRACE_STAMPED
            and "trace_id" not in fields):
        ids = ids_fn()
        if ids is not None:
            fields["trace_id"], fields["span_id"] = ids
    rec = (log.emit(event, **fields) if log is not None
           else {"run_id": "-", "seq": -1, "ts": time.time(),
                 "event": event, **fields})
    if observer is not None:
        observer(rec)
    if recorder is not None:
        recorder(rec)
    return rec if log is not None else None


def read_events(path: str) -> list:
    """Parse a JSONL event log back into records (no validation)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records
