"""Streaming anomaly detection over sampled telemetry series.

Per-watched-series detectors combine an **EWMA** center with a
**MAD**-scaled z-score: the center tracks the series' recent level,
the scale is the median absolute deviation of a bounded residual
window (robust to the very outliers being hunted), and
``z = |value - ewma| / (1.4826 * MAD)``. Detectors are edge-triggered
like every other obs alarm (``slo_breach``, ``degraded_enter``): one
``anomaly_detected`` event per breach episode on the rising edge,
cleared with hysteresis at half the threshold, never one event per
evaluation. Everything is a pure function of the sample stream and
the injectable clock — a fake-clock scripted spike fires exactly one
edge, deterministically (tests/test_timeseries.py).

Watch specs ride the CLI as ``--watch 'NAME:k=v,...'`` (repeatable),
the same grammar shape as ``--slo`` (obs/slo.py):

    --watch 'ingest_lag_seconds:z=6'
    --watch 'tile_cache_stale_serves_total:z=4,alpha=0.2,min_count=16'

``NAME`` matches the flattened series name (histograms flatten to
``<name>_sum``/``<name>_count`` — watching the bare histogram name
watches its per-tick mean). Signal extraction by metric kind: gauges
alarm on the sampled value, counters on the per-tick rate, histograms
on the per-tick mean of new observations — so a watch on
``ingest_lag_seconds`` reads "mean ingest lag this tick", not a
monotonic sum.

The engine plugs into the sampler (``TelemetrySampler(engine=...)``)
and each emitted edge reaches the :class:`~heatmap_tpu.obs.incident.
IncidentManager` as the ``anomaly`` trigger kind, so a latency spike
or ingest-lag runaway flushes a bundle with the surrounding history
embedded (docs/observability.md).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from heatmap_tpu.obs import timeseries

#: Residual window per detector — bounds both memory and how long an
#: old regime biases the MAD.
WINDOW = 32

_EPS = 1e-9

_PARAM_TYPES = {
    "z": float,          # z-score threshold (rising edge)
    "alpha": float,      # EWMA decay toward the newest sample
    "min_count": int,    # warm-up samples before the detector can fire
    "clear_ratio": float,  # hysteresis: clears below z * clear_ratio
}


@dataclass(frozen=True)
class WatchSpec:
    name: str
    z: float = 6.0
    alpha: float = 0.3
    min_count: int = 10
    clear_ratio: float = 0.5


def parse_watch_spec(spec: str) -> WatchSpec:
    """``NAME:k=v,...`` -> :class:`WatchSpec`; raises ``ValueError``
    with the offending token on any malformed input (the CLI converts
    that to a clean SystemExit, same as ``--slo``)."""
    name, _, rest = spec.partition(":")
    name = name.strip()
    if not name:
        raise ValueError(f"watch spec {spec!r}: empty series name")
    params = {}
    for token in filter(None, (t.strip() for t in rest.split(","))):
        key, eq, value = token.partition("=")
        if not eq:
            raise ValueError(f"watch spec {spec!r}: expected k=v, "
                             f"got {token!r}")
        caster = _PARAM_TYPES.get(key)
        if caster is None:
            raise ValueError(f"watch spec {spec!r}: unknown param "
                             f"{key!r} (known: "
                             f"{', '.join(sorted(_PARAM_TYPES))})")
        try:
            params[key] = caster(value)
        except ValueError as e:
            raise ValueError(f"watch spec {spec!r}: bad {key}={value!r}"
                             ) from e
    spec_obj = WatchSpec(name=name, **params)
    if spec_obj.z <= 0 or not (0.0 < spec_obj.alpha <= 1.0):
        raise ValueError(f"watch spec {spec!r}: need z > 0 and "
                         f"0 < alpha <= 1")
    return spec_obj


class SeriesDetector:
    """EWMA center + MAD scale + edge-triggered breach state for one
    series under one watch."""

    def __init__(self, spec: WatchSpec):
        self.spec = spec
        self.ewma: float | None = None
        self.window: deque = deque(maxlen=WINDOW)
        self.count = 0
        self.breaching = False
        self.last_z = 0.0

    def observe(self, value: float) -> bool:
        """Feed one signal value; True exactly on a rising edge."""
        spec = self.spec
        center = self.ewma if self.ewma is not None else value
        residual = value - center
        z = 0.0
        if self.count >= spec.min_count:
            mad = _median([abs(v - _median(list(self.window)))
                           for v in self.window]) if self.window else 0.0
            z = abs(residual) / (1.4826 * mad + _EPS)
        self.last_z = z
        # Update state *after* scoring so the spike itself cannot
        # absorb into the baseline before it is judged.
        self.window.append(value)
        self.ewma = center + spec.alpha * residual
        self.count += 1
        if self.breaching:
            if z < spec.z * spec.clear_ratio:
                self.breaching = False
            return False
        if z >= spec.z and self.count > spec.min_count:
            self.breaching = True
            return True
        return False


def _median(values) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return float(ordered[mid])
    return (ordered[mid - 1] + ordered[mid]) / 2.0


@dataclass
class Anomaly:
    ts: float
    series: str
    watch: str
    value: float
    z: float
    threshold: float

    def to_dict(self) -> dict:
        return {"ts": self.ts, "series": self.series, "watch": self.watch,
                "value": self.value, "z": round(self.z, 3),
                "threshold": self.threshold}


class AnomalyEngine:
    """Watch-list evaluation over sampler ticks.

    ``observe_tick(flat, ts)`` takes the same flattened snapshot the
    sampler appended (``timeseries.flatten_snapshot``), extracts each
    watched series' signal, updates its detector, and emits one
    ``anomaly_detected`` event per rising edge. Recent anomalies are
    ringed for ``/healthz`` and the dashboard.
    """

    def __init__(self, specs, *, clock=time.time, max_recent: int = 64):
        self.specs = list(specs)
        self.clock = clock
        self._detectors: dict[str, SeriesDetector] = {}
        self._prev: dict[str, tuple] = {}
        self._recent: deque = deque(maxlen=max_recent)
        self.edges = 0

    def _signal(self, key: str, kind: str, value: float,
                ts: float) -> float | None:
        """Kind-aware signal: gauge -> value, counter -> per-tick rate,
        histogram mean via the ``_sum``/``_count`` pair (handled by
        spec matching, see :meth:`observe_tick`)."""
        if kind != "counter":
            return value
        prev = self._prev.get(key)
        self._prev[key] = (ts, value)
        if prev is None:
            return None
        dt = ts - prev[0]
        if dt <= 0:
            return None
        return max(0.0, value - prev[1]) / dt

    def observe_tick(self, flat: dict, ts: float | None = None):
        when = self.clock() if ts is None else float(ts)
        for spec in self.specs:
            for key, signal in self._match(spec, flat, when):
                detector = self._detectors.get(key)
                if detector is None:
                    detector = SeriesDetector(spec)
                    self._detectors[key] = detector
                if detector.observe(signal):
                    self._emit(when, key, spec, signal, detector.last_z)

    def _match(self, spec: WatchSpec, flat: dict, when: float):
        """Yield ``(series_key, signal)`` for every flattened series
        the spec names. A watch on a bare histogram name pairs its
        ``_sum``/``_count`` series into a per-tick mean."""
        sum_name, count_name = spec.name + "_sum", spec.name + "_count"
        sums, counts = {}, {}
        for key in sorted(flat):
            name, _labels = timeseries.parse_series_key(key)
            kind, value = flat[key]
            if name == spec.name:
                signal = self._signal(key, kind, value, when)
                if signal is not None:
                    yield key, signal
            elif name == sum_name:
                sums[key[len(sum_name):]] = value
            elif name == count_name:
                counts[key[len(count_name):]] = value
        for labels_part, count in sorted(counts.items()):
            total = sums.get(labels_part)
            if total is None:
                continue
            pair_key = spec.name + labels_part
            prev = self._prev.get(pair_key)
            self._prev[pair_key] = (count, total)
            if prev is None:
                continue
            d_count = count - prev[0]
            if d_count <= 0:
                continue
            yield pair_key, (total - prev[1]) / d_count

    def _emit(self, when, key, spec, value, z):
        from heatmap_tpu.obs import events

        anomaly = Anomaly(ts=when, series=key, watch=spec.name,
                          value=float(value), z=float(z),
                          threshold=spec.z)
        self._recent.append(anomaly)
        self.edges += 1
        from heatmap_tpu import obs

        if obs.metrics_enabled():
            obs.ANOMALIES_TOTAL.inc(watch=spec.name)
        events.emit("anomaly_detected", series=key, z=round(float(z), 3),
                    threshold=spec.z, watch=spec.name,
                    value=float(value))

    def recent(self, n: int = 16) -> list:
        return [a.to_dict() for a in list(self._recent)[-n:]]

    def status(self) -> dict:
        return {
            "watches": [{"name": s.name, "z": s.z, "alpha": s.alpha,
                         "min_count": s.min_count} for s in self.specs],
            "series_tracked": len(self._detectors),
            "breaching": sorted(k for k, d in self._detectors.items()
                                if d.breaching),
            "edges": self.edges,
            "recent": self.recent(),
        }


# -- module state -----------------------------------------------------------

_engine: AnomalyEngine | None = None


def set_engine(engine: AnomalyEngine | None):
    global _engine
    _engine = engine


def get_engine() -> AnomalyEngine | None:
    return _engine
