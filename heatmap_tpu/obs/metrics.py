"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Same zero-cost discipline as ``utils.trace.stage_span``: every mutation
checks the registry's ``enabled`` flag first and returns immediately when
no sink is configured, so instrumented hot paths pay one attribute read
and one boolean test when telemetry is off.

Metrics carry a fixed set of label names declared at creation time
(``counter("points_binned_total", labelnames=("backend",))``); each
distinct label-value tuple becomes its own time series, mirroring the
Prometheus data model. ``render_prometheus`` writes the text exposition
format (``# HELP`` / ``# TYPE`` plus ``name{label="v"} value`` lines,
histogram ``_bucket``/``_sum``/``_count`` with a ``+Inf`` bucket) so a
``--metrics-dir`` dump can be scraped or diffed directly.

The module-level default registry is the process-wide instance every
instrumentation site uses (``get_registry()`` — the ``get_tracer()``
pattern); tests reset it between cases via the autouse fixture in
tests/conftest.py.
"""

from __future__ import annotations

import bisect
import os
import re
import threading

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Exemplar identity hook: installed by obs.tracing.enable_tracing (the
# events._trace_ids pattern); returns the ambient (trace_id, span_id)
# so each histogram bucket remembers the last trace that landed in it.
_exemplar_ids = None

# Wall-clock seconds; spans range from sub-ms host hops to multi-minute
# ingest scans, so the grid is log-ish from 1ms to ~2min.
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   30.0, 60.0, 120.0)


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value: float) -> str:
    if value == float("inf"):
        return "+Inf"
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return repr(value) if isinstance(value, float) else str(value)


class _Metric:
    """Base: label validation + the shared registry lock."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labelnames: tuple):
        self._registry = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._values: dict = {}

    def _key(self, labels: dict) -> tuple:
        if len(labels) != len(self.labelnames) or any(
                k not in labels for k in self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def clear(self):
        with self._registry._lock:
            self._values.clear()

    def samples(self) -> dict:
        """Snapshot ``{label-tuple: value}`` (value shape is kind-specific)."""
        with self._registry._lock:
            return dict(self._values)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1, **labels):
        reg = self._registry
        if not reg.enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        key = self._key(labels)
        with reg._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels) -> float:
        with self._registry._lock:
            return self._values.get(self._key(labels), 0)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels):
        reg = self._registry
        if not reg.enabled:
            return
        key = self._key(labels)
        with reg._lock:
            self._values[key] = value

    def inc(self, amount: float = 1, **labels):
        reg = self._registry
        if not reg.enabled:
            return
        key = self._key(labels)
        with reg._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels) -> float:
        with self._registry._lock:
            return self._values.get(self._key(labels), 0)


class Histogram(_Metric):
    """Fixed-bucket histogram; per-series state is ``[counts, sum, n]``
    where ``counts[i]`` is the number of observations <= buckets[i]
    (non-cumulative per bucket; cumulated at render time)."""

    kind = "histogram"

    def __init__(self, registry, name, help, labelnames,
                 buckets=DEFAULT_BUCKETS):
        super().__init__(registry, name, help, labelnames)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs or any(b != b or b == float("inf") for b in bs):
            raise ValueError("histogram buckets must be finite and non-empty")
        self.buckets = bs
        # (series-key, bucket-idx) -> (trace_id, span_id, value): the
        # last trace that landed in each bucket (OpenMetrics exemplar).
        # Kept out of the per-series state list so samples() consumers
        # still unpack [counts, sum, n].
        self._exemplars: dict = {}

    def observe(self, value: float, **labels):
        reg = self._registry
        if not reg.enabled:
            return
        key = self._key(labels)
        idx = bisect.bisect_left(self.buckets, value)
        ids_fn = _exemplar_ids
        ids = ids_fn() if ids_fn is not None else None
        with reg._lock:
            state = self._values.get(key)
            if state is None:
                state = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._values[key] = state
            state[0][idx] += 1
            state[1] += value
            state[2] += 1
            if ids is not None:
                self._exemplars[(key, idx)] = (ids[0], ids[1], value)

    def samples(self) -> dict:
        with self._registry._lock:
            return {k: [list(v[0]), v[1], v[2]]
                    for k, v in self._values.items()}

    def exemplars(self) -> dict:
        """Snapshot ``{(series-key, bucket-idx): (trace_id, span_id,
        value)}`` — the last observation that landed in each bucket
        while tracing supplied an ambient identity."""
        with self._registry._lock:
            return dict(self._exemplars)

    def clear(self):
        with self._registry._lock:
            self._values.clear()
            self._exemplars.clear()


class MetricsRegistry:
    """Thread-safe, process-wide home for all metrics.

    Creation is get-or-create: asking for an existing name returns the
    same object; asking with a different kind or label set raises, so
    two call sites cannot silently fork a series.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict = {}
        self.enabled = False

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        labelnames = tuple(labelnames)
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or (
                        existing.labelnames != labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.labelnames}")
                return existing
            metric = cls(self, name, help, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labelnames: tuple = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: tuple = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "", labelnames: tuple = (),
                  buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def reset(self):
        """Clear all recorded values. Metric *definitions* (and the
        objects instrumentation sites hold) stay registered, so cached
        handles in obs/__init__ remain valid across test resets."""
        with self._lock:
            for m in self._metrics.values():
                m._values.clear()
                exemplars = getattr(m, "_exemplars", None)
                if exemplars is not None:
                    exemplars.clear()

    def snapshot(self) -> dict:
        """JSON-ready dump: ``{name: {type, help, labelnames, samples}}``
        where samples is a list of ``{labels, value}`` (counter/gauge)
        or ``{labels, buckets, sum, count}`` (histogram)."""
        out = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            entries = []
            exemplars = (m.exemplars() if m.kind == "histogram" else {})
            for key, val in sorted(m.samples().items()):
                labels = dict(zip(m.labelnames, key))
                if m.kind == "histogram":
                    counts, total, n = val
                    cum, acc = {}, 0
                    for b, c in zip(m.buckets + (float("inf"),), counts):
                        acc += c
                        cum[_fmt(b)] = acc
                    entry = {"labels": labels, "buckets": cum,
                             "sum": total, "count": n}
                    ex = {}
                    bounds = m.buckets + (float("inf"),)
                    for idx, bound in enumerate(bounds):
                        hit = exemplars.get((key, idx))
                        if hit is not None:
                            ex[_fmt(bound)] = {"trace_id": hit[0],
                                               "span_id": hit[1],
                                               "value": hit[2]}
                    if ex:
                        entry["exemplars"] = ex
                    entries.append(entry)
                else:
                    entries.append({"labels": labels, "value": val})
            out[m.name] = {"type": m.kind, "help": m.help,
                           "labelnames": list(m.labelnames),
                           "samples": entries}
        return out

    def render_prometheus(self) -> str:
        """Text exposition format (version 0.0.4)."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            samples = m.samples()
            if not samples:
                continue
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, val in sorted(samples.items()):
                base = ",".join(
                    f'{ln}="{_escape_label(lv)}"'
                    for ln, lv in zip(m.labelnames, key))
                if m.kind == "histogram":
                    counts, total, n = val
                    exemplars = m.exemplars()
                    acc = 0
                    for idx, (b, c) in enumerate(
                            zip(m.buckets + (float("inf"),), counts)):
                        acc += c
                        le = (base + "," if base else "") + f'le="{_fmt(b)}"'
                        line = f"{m.name}_bucket{{{le}}} {acc}"
                        hit = exemplars.get((key, idx))
                        if hit is not None:
                            # OpenMetrics-style exemplar: the last
                            # trace that landed in this bucket.
                            line += (f' # {{trace_id="{hit[0]}",'
                                     f'span_id="{hit[1]}"}} '
                                     f"{_fmt(hit[2])}")
                        lines.append(line)
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(f"{m.name}_sum{suffix} {_fmt(total)}")
                    lines.append(f"{m.name}_count{suffix} {n}")
                else:
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(f"{m.name}{suffix} {_fmt(val)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_prometheus(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.render_prometheus())
        os.replace(tmp, path)


_default = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry all instrumentation records into."""
    return _default


def enable_metrics(on: bool = True):
    _default.enabled = bool(on)


def metrics_enabled() -> bool:
    return _default.enabled
