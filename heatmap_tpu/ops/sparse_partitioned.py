"""Partitioned-MXU segment reduction for the cascade's sorted streams.

``aggregate_sorted_keys`` (ops/sparse.py) costs two ~8-30 ns/element
scatters per cascade level on v5e — the sums ``.at[seg].add`` and the
unique-keys ``.at[seg].set`` — 32 scatters across a 16-level cascade,
the dominant device cost of the batch job (PERF_NOTES.md). This module
reformulates BOTH as one pass of the measured-2.2x sort-partitioned
one-hot-matmul machinery (ops/partitioned.py), exploiting that the
cascade's inputs are already sorted:

- the segment index ``seg = cumsum(first) - 1`` is a sorted, dense
  cell id into [0, capacity) — exactly the stream shape the
  partitioned kernel bins, with NO sort needed;
- counts stay exact at any fan-in by processing the stream in SLABS
  of at most 2^24 elements: per-slab f32 accumulation cannot round
  (every partial sum is an integer < 2^24), and slabs combine in f64
  on the way out;
- the unique key of a segment is reconstructed through three extra
  matmul CHANNELS: the segment's FIRST element contributes its key
  split into 20-bit pieces (each < 2^20, exactly one contribution per
  segment globally, so f32 holds them exactly), and the pieces
  reassemble as ``lo | mid<<20 | hi<<40`` — covering keys up to 60
  bits, which includes the cascade's 58-bit composite keys. The
  one-hot construction (the VPU cost that bounds the partitioned
  kernel) is SHARED by all four channels; the extra matmuls ride the
  MXU.

Counts AND bounded-integer weights: the slab argument extends to
weights that are integers in ``[0, weight_bound]`` — per-slab
per-cell partials are integers <= ``slab * weight_bound``, exact in
f32 when the slab shrinks to ``2^24 // weight_bound`` elements, and
slabs still combine exactly in f64 (sums < 2^53). Weighted calls add
a fifth channel (segment PRESENCE, one f32 unit per segment) so
zero-sum segments survive with their keys — bit-parity with the
scatter path. FRACTIONAL weights genuinely cannot ride this kernel:
f32 products of non-integer weights round before accumulation, and
there is no slab size that restores exactness — those stay on the
scatter path (the precise boundary VERDICT r4 #7 asked for). Keys
must fit 60 bits (a caller contract; the cascade's composite keys do
by the int64 packing check in pipeline/cascade.composite_keys).

STATUS: interpret-mode verified (tests/test_sparse_partitioned.py,
bit-equal to aggregate_sorted_keys including multi-slab and fallback
paths) AND compiled + bit-exact on v5e under real Mosaic lowering
(2026-07-31, clustered 1M-key drive, after the x64 int32-constant
fixes). The on-chip WIN measurement (cascade suite of
tools/sweep_partitioned.py) decides whether
BatchJobConfig.cascade_backend defaults here — nothing routes here by
default yet.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from heatmap_tpu.ops.histogram import IMAP_ZERO
from heatmap_tpu.ops.partitioned import masked_local_rc

DEFAULT_CHUNK = 1024
DEFAULT_BLOCK_CELLS = 1 << 16
#: Max elements per exactness slab: f32 integer accumulation is exact
#: below 2^24, and a slab contributes at most ``slab`` to any count.
DEFAULT_SLAB = 1 << 24
#: Bits per key-reconstruction channel (3 channels -> 60-bit keys).
KEY_BITS = 20
N_CHANNELS = 4  # counts + 3 key pieces
N_CHANNELS_WEIGHTED = 5  # weighted sums + presence + 3 key pieces


def _segment_kernel(base_ref, good_ref, first_v_ref, last_v_ref,
                    s_ref, w_ref, zeros_ref, out_ref, acc_ref, *,
                    chunk, block_cells, side, n_blocks,
                    n_channels=N_CHANNELS):
    """Multi-channel twin of partitioned._partition_kernel: one shared
    one-hot pair per chunk, ``n_channels`` weighted matmuls into a
    (1, n_channels, side, side) accumulator."""
    del zeros_ref
    i = pl.program_id(0)

    @pl.when(first_v_ref[i] == 1)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # base_ref holds FLAT output-slab ids stream*n_blocks + block
    # (streams=1 makes this the plain block id); the cell offset
    # depends only on the block part — same scheme as the window
    # kernel (ops/partitioned.py).
    rloc, cloc = masked_local_rc(
        base_ref[i] % jnp.int32(n_blocks), good_ref[i], s_ref[0, 0, :],
        block_cells, side,
    )

    r_ids = lax.broadcasted_iota(jnp.int32, (side, chunk), 0)
    c_ids = lax.broadcasted_iota(jnp.int32, (chunk, side), 1)
    row_onehot = (r_ids == rloc[None, :]).astype(jnp.float32)
    col_onehot = (c_ids == cloc[:, None]).astype(jnp.float32)
    for ch in range(n_channels):  # static unroll; one-hots shared
        # HIGHEST, not the MXU default: channel values run to 2^20 (key
        # pieces) and the default f32 matmul may execute as one bf16
        # pass (8 mantissa bits — observed on-chip 2026-08-02, keys
        # truncated to 1024-multiples at slab=2^20). The one-hot factor
        # is exact in any precision; the VALUE factor is not.
        acc_ref[0, ch] += jnp.dot(
            row_onehot, col_onehot * w_ref[0, ch, :][:, None],
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )

    @pl.when(last_v_ref[i] == 1)
    def _():
        out_ref[:] = acc_ref[:]


def _good_of(cells, chunk, block_cells, capacity):
    """Per-chunk goodness: fully inside one aligned block AND free of
    dropped lanes (cell id == capacity)."""
    first = cells[::chunk]
    last = cells[chunk - 1 :: chunk]
    return (first // block_cells == last // block_cells) & (last < capacity)


def _channel_path(cells, chans, good, capacity, n_blocks, chunk,
                  bad_cap_chunks, interpret, block_cells, side,
                  streams=1, n_channels=N_CHANNELS):
    """Good chunks -> multi-channel pallas blocks; bad chunks ->
    bounded f64 scatter tails (exact: every channel is integer-valued
    below 2^52). ``good`` is the caller's per-chunk mask — the same
    one that sized the bounded tail.

    ``streams`` splits the (globally sorted) slab into that many
    contiguous sub-streams, each accumulating into its own slab of
    output blocks, summed at the end — the same grid-pipelining trick
    the window kernel's streams=8 default bought 2.0x from
    (PERF_NOTES 2026-07-31). Identical math: counts and key-piece
    channels are linear, every segment's FIRST element lands in
    exactly one sub-stream, and chunk boundaries are unchanged
    (sub-streams are whole runs of chunks), so the bad-chunk tail is
    untouched. The sub-slab sums stay f32-exact: each slab holds at
    most 2^24 elements total, so every per-cell partial and the
    cross-stream integer sum are <= 2^24."""
    L = cells.shape[0]
    nck = L // chunk
    # Forward-fill bad chunks with the last good block id per
    # sub-stream (each sub-stream is a contiguous slice of the sorted
    # slab, so good block ids are non-decreasing within it); leading
    # bads clamp to block 0, fully masked.
    first2 = cells.reshape(streams, L // streams)[:, ::chunk]
    good2 = good.reshape(streams, nck // streams)
    base2 = jnp.maximum(
        lax.cummax(jnp.where(good2, first2 // block_cells, -1), axis=1), 0
    ).astype(jnp.int32)
    # Flat output-slab id stream*n_blocks + block: monotone within a
    # sub-stream, strictly increasing across sub-stream boundaries'
    # slabs -> visit runs stay consecutive over the flattened grid.
    base = (
        jnp.arange(streams, dtype=jnp.int32)[:, None] * jnp.int32(n_blocks)
        + base2
    ).reshape(-1)
    gi = good.astype(jnp.int32)
    first_visit = jnp.concatenate(
        [jnp.ones(1, jnp.int32), (base[1:] != base[:-1]).astype(jnp.int32)]
    )
    last_visit = jnp.concatenate(
        [(base[1:] != base[:-1]).astype(jnp.int32), jnp.ones(1, jnp.int32)]
    )

    from jax.experimental.pallas import tpu as pltpu

    z = IMAP_ZERO  # concrete int32; see histogram.IMAP_ZERO
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(nck,),
        in_specs=[
            # (nck, 1, chunk): last-two block dims (1, chunk) satisfy
            # the TPU tiling rule (sublane == array dim, lane % 128).
            pl.BlockSpec((1, 1, chunk), lambda i, *_: (i, z, z)),
            # (nck, n_channels, chunk): channel dim taken whole.
            pl.BlockSpec((1, n_channels, chunk), lambda i, *_: (i, z, z)),
            pl.BlockSpec(
                (1, n_channels, side, side),
                lambda i, base_, *_: (base_[i], z, z, z),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, n_channels, side, side),
            lambda i, base_, *_: (base_[i], z, z, z),
        ),
        scratch_shapes=[
            pltpu.VMEM((1, n_channels, side, side), jnp.float32)
        ],
    )
    zeros = jnp.zeros((streams * n_blocks, n_channels, side, side),
                      jnp.float32)

    def _kernel_call(base_, gi_, first_, last_, cells_, chans_, zeros_):
        return pl.pallas_call(
            functools.partial(_segment_kernel, chunk=chunk,
                              block_cells=block_cells, side=side,
                              n_blocks=n_blocks, n_channels=n_channels),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(
                (streams * n_blocks, n_channels, side, side), jnp.float32
            ),
            input_output_aliases={6: 0},  # zeros operand -> output
            interpret=interpret,
        )(base_, gi_, first_, last_, cells_, chans_, zeros_)

    # vmap of a pallas_call whose scalar-prefetch operands are batched
    # (the gspmd dispatch vmaps this whole stage over the shard axis)
    # falls back to jax's explicit batch loop, whose weak-typed
    # fori_loop counter lands as s64 under x64; the SPMD partitioner
    # then compares that s64 update index against its own s32 shard
    # offsets and the HLO verifier rejects the module ("Binary op
    # compare with different element types: s64[] and s32[]"). The
    # batch axis is the static shard count, so unroll it instead:
    # constant-index slices of a shard-dim-sharded operand are exactly
    # what the partitioner handles natively — no dynamic update index
    # of either width, and no per-iteration collectives either.
    kernel_call = jax.custom_batching.custom_vmap(_kernel_call)

    @kernel_call.def_vmap
    def _kernel_vmap_rule(axis_size, in_batched, *args):
        outs = [
            _kernel_call(*[a[i] if b else a
                           for a, b in zip(args, in_batched)])
            for i in range(axis_size)
        ]
        return jnp.stack(outs), True

    blocks = kernel_call(
        base, gi, first_visit, last_visit,
        cells.reshape(nck, 1, chunk),
        chans.reshape(n_channels, nck, chunk).transpose(1, 0, 2),
        zeros)
    if streams > 1:
        blocks = blocks.reshape(
            streams, n_blocks, n_channels, side, side
        ).sum(axis=0)
    dense = blocks.transpose(1, 0, 2, 3).reshape(
        n_channels, n_blocks * block_cells
    )[:, :capacity]

    bad_idx = jnp.nonzero(~good, size=bad_cap_chunks, fill_value=nck)[0]
    bad_cells = jnp.take(cells.reshape(nck, chunk), bad_idx, axis=0,
                         mode="fill", fill_value=capacity).reshape(-1)
    tails = []
    for ch in range(n_channels):
        bad_w = jnp.take(chans[ch].reshape(nck, chunk), bad_idx, axis=0,
                         mode="fill", fill_value=0.0).reshape(-1)
        tails.append(
            jnp.zeros(capacity, jnp.float64)
            .at[bad_cells]
            .add(bad_w.astype(jnp.float64), mode="drop")
        )
    return dense.astype(jnp.float64) + jnp.stack(tails)


@functools.partial(
    jax.jit,
    static_argnames=("capacity", "chunk", "block_cells", "bad_frac",
                     "slab", "interpret", "streams", "weight_bound"),
)
def aggregate_sorted_keys_partitioned(
    sorted_keys,
    capacity: int,
    sentinel=None,
    chunk: int = DEFAULT_CHUNK,
    block_cells: int = DEFAULT_BLOCK_CELLS,
    bad_frac: int = 8,
    slab: int = DEFAULT_SLAB,
    interpret: bool | None = None,
    streams: int = 1,
    sorted_weights=None,
    weight_bound: int | None = None,
):
    """``aggregate_sorted_keys`` on the partitioned kernel.

    Contract matches ops.sparse.aggregate_sorted_keys: returns
    (unique[capacity] int64, sums[capacity], n_unique); slots past
    n_unique hold sentinel/zero; exact at ANY per-key fan-in
    (slab-wise f32 accumulation, f64 combine). With the default unit
    weights, sums are int32 counts. ``slab`` is a parameter so tests
    can exercise the multi-slab combine at small sizes; it must be a
    multiple of ``streams * chunk``. ``streams`` splits each slab into
    contiguous sub-streams with per-stream output slabs (see
    _channel_path; bit-identical results, measured for grid pipelining
    on-chip before any default flips — costs ``streams`` x the
    output-blocks buffer).

    ``sorted_weights`` (same order as ``sorted_keys``) switches to the
    weighted 5-channel form: sums are f64 per-key weight totals, exact
    PROVIDED every weight is an integer in ``[0, weight_bound]``
    (required, static) — the exactness slab shrinks to
    ``2^24 // weight_bound`` elements (see module docstring). Weights
    violating the contract are detected ON DEVICE and poison
    ``n_unique`` past ``capacity`` — the repo-wide overflow signal —
    so a fractional or oversized weight can never produce a silently
    rounded sum. Fractional weights are fundamentally outside this
    kernel (f32 products round before accumulation; no slab size
    restores exactness): use the scatter path.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    keys = jnp.asarray(sorted_keys)
    if sentinel is None:
        sentinel = jnp.iinfo(keys.dtype).max
    if keys.dtype != jnp.int64:
        keys = keys.astype(jnp.int64)
        sentinel = jnp.int64(sentinel)
    n = keys.shape[0]
    if streams < 1:
        raise ValueError(f"streams must be >= 1, got {streams}")
    weighted = sorted_weights is not None
    if weighted:
        if weight_bound is None or weight_bound < 1:
            raise ValueError(
                "weighted partitioned reduction needs a positive "
                "static weight_bound (exactness slab = 2^24 // bound)"
            )
        # Shrink the slab so per-cell per-slab partials stay integers
        # < 2^24 (f32-exact); it must stay a multiple of streams*chunk.
        # When the bound is so large that even ONE chunk row per stream
        # exceeds the exactness budget, no slab size can keep the f32
        # accumulator exact — refuse loudly instead of silently
        # flooring the slab and rounding sums (the kernel's whole
        # contract is "never a silently rounded sum").
        unit = streams * chunk
        exact_slab = ((1 << 24) // weight_bound) // unit * unit
        if exact_slab < unit:
            raise ValueError(
                f"weight_bound {weight_bound} is too large for the "
                f"exactness slab: 2^24 // bound = "
                f"{(1 << 24) // weight_bound} elements, below one "
                f"chunk row per stream (streams*chunk = {unit}) — "
                f"shrink chunk/streams or the bound (max bound at "
                f"this geometry: {(1 << 24) // unit}), or use the "
                "scatter backend"
            )
        slab = min(slab, exact_slab)
    if slab % (streams * chunk):
        raise ValueError(
            f"slab {slab} must be a multiple of streams*chunk "
            f"({streams}*{chunk})"
        )
    side = 1 << (block_cells.bit_length() // 2)
    if side * side != block_cells or side < 64:
        raise ValueError(
            f"block_cells must be an even power of two >= 4096, "
            f"got {block_cells}"
        )

    is_real = keys != sentinel
    first = jnp.concatenate(
        [jnp.ones((1,), bool), keys[1:] != keys[:-1]]
    ) & is_real
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    cells = jnp.where(is_real, seg, capacity)  # capacity == drop
    n_unique = jnp.sum(first.astype(jnp.int32))

    # Channels: counts (or weighted sums + presence) + the
    # segment-first element's key in 20-bit pieces (one nonzero
    # contribution per segment -> f32-exact).
    fw = first.astype(jnp.float32)
    mask = (1 << KEY_BITS) - 1
    pieces = [
        fw * ((keys >> 0) & mask).astype(jnp.float32),
        fw * ((keys >> KEY_BITS) & mask).astype(jnp.float32),
        fw * ((keys >> (2 * KEY_BITS)) & mask).astype(jnp.float32),
    ]
    if weighted:
        wts = jnp.asarray(sorted_weights)
        # Contract check ON DEVICE: integers in [0, weight_bound].
        # Violations poison n_unique (the overflow signal) below —
        # never a silently rounded sum.
        wf64 = wts.astype(jnp.float64)
        bad_weights = (
            (wf64 != jnp.floor(wf64)) | (wf64 < 0)
            | (wf64 > weight_bound)
        ) & is_real
        weights_invalid = bad_weights.any()
        w32 = jnp.where(is_real, wts.astype(jnp.float32), 0.0)
        chans = jnp.stack([w32, is_real.astype(jnp.float32)] + pieces)
        n_channels = N_CHANNELS_WEIGHTED
    else:
        chans = jnp.stack([is_real.astype(jnp.float32)] + pieces)
        n_channels = N_CHANNELS

    # Pad to whole slabs of whole chunks.
    n_slabs = max(1, -(-max(n, 1) // slab))
    n_pad = n_slabs * slab
    if n_pad != n:
        cells = jnp.concatenate(
            [cells, jnp.full(n_pad - n, capacity, cells.dtype)]
        )
        chans = jnp.concatenate(
            [chans, jnp.zeros((n_channels, n_pad - n), jnp.float32)], axis=1
        )

    n_blocks = -(-capacity // block_cells)
    sums = jnp.zeros((n_channels, capacity), jnp.float64)
    for s in range(n_slabs):  # static unroll: ~n/2^24 iterations
        c_slab = cells[s * slab : (s + 1) * slab]
        ch_slab = chans[:, s * slab : (s + 1) * slab]
        nck = slab // chunk
        bad_cap = max(2, nck // bad_frac)
        good_slab = _good_of(c_slab, chunk, block_cells, capacity)
        n_bad = (~good_slab).sum()

        def scatter_all(c_, ch_, g_):
            return jnp.stack([
                jnp.zeros(capacity, jnp.float64)
                .at[c_]
                .add(ch_[ch].astype(jnp.float64), mode="drop")
                for ch in range(n_channels)
            ])

        slab_sums = lax.cond(
            n_bad <= bad_cap,
            lambda c_, ch_, g_: _channel_path(
                c_, ch_, g_, capacity, n_blocks, chunk, bad_cap,
                interpret, block_cells, side, streams=streams,
                n_channels=n_channels,
            ),
            scatter_all,
            c_slab,
            ch_slab,
            good_slab,
        )
        sums = sums + slab_sums

    pc = 1 if weighted else 0  # presence channel index
    present = jnp.round(sums[pc]) > 0
    key_lo = jnp.round(sums[pc + 1]).astype(jnp.int64)
    key_mid = jnp.round(sums[pc + 2]).astype(jnp.int64)
    key_hi = jnp.round(sums[pc + 3]).astype(jnp.int64)
    unique = key_lo | (key_mid << KEY_BITS) | (key_hi << (2 * KEY_BITS))
    unique = jnp.where(present, unique, sentinel)
    if weighted:
        totals = jnp.where(present, sums[0], 0.0)
        n_unique = jnp.where(
            weights_invalid,
            jnp.maximum(n_unique, capacity + 1), n_unique,
        )
        return unique, totals, n_unique
    counts = jnp.round(sums[0]).astype(jnp.int32)
    return unique, counts, n_unique
