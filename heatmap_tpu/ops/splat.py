"""Gaussian kernel splatting: weighted points -> smoothed heat rasters.

BASELINE.md config 3 — "weighted heatmap (per-point value sum) + 9x9
Gaussian-kernel splat per tile". The reference job only ever counts
(count=1.0 per row, reference heatmap.py:35); weighting and kernel
smoothing are new framework surface.

TPU-native formulation: splatting each point's 9x9 stamp individually
would be 81 scatters per point — instead we scatter-add the weighted
points once (ops.histogram) and then convolve the raster with the
kernel. The convolution is **separable** (outer product of two 1D
Gaussians), so it runs as two `lax.conv_general_dilated` passes —
dense, static-shaped MXU work that XLA pipelines from HBM, exactly the
op class TPUs are built for. Mathematically identical to per-point
stamping because convolution distributes over the sum of point masses.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from heatmap_tpu.ops.histogram import Window, bin_points_window


def gaussian_kernel_1d(size: int = 9, sigma: float | None = None, dtype=jnp.float32):
    """Normalized 1D Gaussian taps. ``sigma`` defaults to size/4
    (sigma=2.25 for 9 taps), so the kernel truncates at ~2 sigma each
    side and renormalizes the ~4% clipped tail mass back in."""
    if size < 1 or size % 2 == 0:
        raise ValueError(f"kernel size must be odd and positive, got {size}")
    if sigma is None:
        sigma = size / 4.0
    if not sigma > 0:
        raise ValueError(f"sigma must be positive, got {sigma}")
    x = np.arange(size, dtype=np.float64) - (size - 1) / 2.0
    k = np.exp(-0.5 * (x / sigma) ** 2)
    k /= k.sum()
    return jnp.asarray(k, dtype)


def splat_raster(raster, kernel_1d):
    """Separable SAME convolution of an (H, W) raster with the outer
    product of ``kernel_1d`` with itself. Returns same shape/dtype
    float raster."""
    k = jnp.asarray(kernel_1d)
    x = jnp.asarray(raster)
    out_dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else k.dtype
    x = x.astype(out_dtype)[None, None]  # NCHW
    kv = k.astype(out_dtype)[None, None, :, None]  # OIHW, vertical taps
    kh = k.astype(out_dtype)[None, None, None, :]  # horizontal taps
    half = (k.shape[0] - 1) // 2
    x = lax.conv_general_dilated(x, kv, (1, 1), [(half, half), (0, 0)])
    x = lax.conv_general_dilated(x, kh, (1, 1), [(0, 0), (half, half)])
    return x[0, 0]


def bin_points_splat(
    latitude,
    longitude,
    window: Window,
    weights=None,
    valid=None,
    kernel_size: int = 9,
    sigma: float | None = None,
    proj_dtype=None,
    dtype=None,
):
    """Config-3 fused step: project -> weighted scatter-add -> 9x9
    Gaussian splat. ``weights=None`` splats plain counts, accumulated
    exactly in i32 (histogram policy, SURVEY.md §8.8 — f32 counting
    saturates at 2^24/cell) and promoted to float by the convolution.
    Total mass of in-window interior points is preserved (kernel sums
    to 1); mass within ``kernel_size//2`` cells of the window edge
    bleeds out, as with any SAME-padded stamp."""
    raster = bin_points_window(
        latitude, longitude, window,
        weights=weights, valid=valid, proj_dtype=proj_dtype, dtype=dtype,
    )
    kernel_dtype = (
        raster.dtype
        if jnp.issubdtype(raster.dtype, jnp.floating)
        else jnp.float32
    )
    return splat_raster(raster, gaussian_kernel_1d(kernel_size, sigma, kernel_dtype))
