"""Aggregation kernels: the TPU-native replacement for Spark's shuffles.

The reference aggregates with reduceByKey/groupByKey over string keys
(reference heatmap.py:111-112; 32 shuffles per run, SURVEY.md §3.3).
Here the same work is three jit-compiled primitives:

- ``histogram``: dense window-raster scatter-add — points -> (H, W)
  counts for a bounded tile window at one zoom.
- ``sparse``: fixed-capacity sort + segment-sum over integer keys —
  the global / per-user aggregation path, XLA-friendly (static shapes,
  no data-dependent control flow).
- ``pyramid``: zoom rollups — 2x2 reshape-sums on rasters, and
  order-preserving Morton-shift re-aggregation on sparse keys.
- ``splat``: weighted binning + separable Gaussian-kernel smoothing
  (BASELINE.md config 3), dense MXU convolution work.
"""

from heatmap_tpu.ops.histogram import (  # noqa: F401
    Window,
    bin_points_window,
    bin_rowcol_window,
    window_from_bounds,
)
from heatmap_tpu.ops.sparse import (  # noqa: F401
    aggregate_keys,
    aggregate_sorted_keys,
)
from heatmap_tpu.ops.pyramid import (  # noqa: F401
    coarsen_raster,
    pyramid_from_raster,
    pyramid_sparse_morton,
)
from heatmap_tpu.ops.splat import (  # noqa: F401
    bin_points_splat,
    gaussian_kernel_1d,
    splat_raster,
)
