"""Dense window-raster histograms: points -> per-tile counts.

This is the hot binning path (BASELINE.md north star). A ``Window`` is a
static, axis-aligned block of the global tile grid at one zoom; points
are projected, localized, and scatter-added into an (H, W) raster. The
reference's storage unit — a coarse tile holding a 32x32 dict of detail
counts (reference heatmap.py:16,89) — is a special case: a 32x32 window
5 zooms below the coarse tile.

Accumulation dtype policy (SURVEY.md §8.8): the reference sums float
1.0s, which silently stops incrementing at 2^24 per tile in f32. Counts
accumulate in int32 here (weights=None) and only become floats at the
egress boundary; weighted sums accumulate in f32 by default.
"""

from __future__ import annotations

import dataclasses
import math

import jax.numpy as jnp
import numpy as _np

from heatmap_tpu.tilemath import mercator
from heatmap_tpu.tilemath import tile as _tile


@dataclasses.dataclass(frozen=True)
class Window:
    """A static (hashable -> jit-friendly) tile-grid window at one zoom.

    Covers rows [row0, row0+height) x cols [col0, col0+width) at ``zoom``.
    """

    zoom: int
    row0: int
    col0: int
    height: int
    width: int

    def __post_init__(self):
        n = 1 << self.zoom
        if self.height <= 0 or self.width <= 0:
            raise ValueError(f"window has empty extent: {self}")
        if not (0 <= self.row0 and self.row0 + self.height <= n):
            raise ValueError(f"window rows outside grid at z{self.zoom}: {self}")
        if not (0 <= self.col0 and self.col0 + self.width <= n):
            raise ValueError(f"window cols outside grid at z{self.zoom}: {self}")

    @property
    def shape(self):
        return (self.height, self.width)

    def aligned_to(self, levels: int) -> bool:
        """True if the window sits on 2^levels tile boundaries, so a
        ``levels``-deep reshape-sum pyramid stays aligned to the global
        grid (ops/pyramid.py)."""
        a = 1 << levels
        return (
            self.row0 % a == 0
            and self.col0 % a == 0
            and self.height % a == 0
            and self.width % a == 0
        )


def window_from_bounds(
    lat_range,
    lon_range,
    zoom: int,
    align_levels: int = 0,
    pad_multiple: int = 1,
) -> Window:
    """Smallest Window covering a lat/lon bounding box, grid-aligned.

    ``align_levels`` rounds the window out to 2^levels boundaries (for
    pyramid alignment); ``pad_multiple`` additionally pads height/width
    up to a multiple (e.g. 256 to keep rasters TPU-lane friendly).
    Alignment is guaranteed (or a ValueError); the pad multiple is
    best-effort — it clamps to the grid size when the z``zoom`` grid is
    smaller than the requested multiple, so callers needing exact
    divisibility (e.g. row-sharding) must check the returned shape.
    """
    if align_levels > zoom:
        raise ValueError(
            f"align_levels={align_levels} exceeds zoom={zoom}: the grid has "
            f"only 2^{zoom} tiles per side, so 2^{align_levels}-alignment is "
            "impossible"
        )
    lat_lo, lat_hi = min(lat_range), max(lat_range)
    lon_lo, lon_hi = min(lon_range), max(lon_range)
    n = 1 << zoom
    # Rows grow southward: the high latitude gives the low row.
    r_lo = int(_tile._row_from_latitude(min(lat_hi, mercator.MAX_LATITUDE), zoom))
    r_hi = int(_tile._row_from_latitude(max(lat_lo, -mercator.MAX_LATITUDE), zoom))
    c_lo = int(_tile._column_from_longitude(lon_lo, zoom))
    c_hi = int(_tile._column_from_longitude(lon_hi, zoom))
    r_lo, c_lo = max(r_lo, 0), max(c_lo, 0)
    r_hi, c_hi = min(r_hi, n - 1), min(c_hi, n - 1)
    if r_hi < r_lo or c_hi < c_lo:
        raise ValueError(
            f"bounds lat={lat_range} lon={lon_range} cover no tiles at z{zoom}"
        )

    a = 1 << align_levels
    row0 = (r_lo // a) * a
    col0 = (c_lo // a) * a
    height = -((-(r_hi + 1 - row0)) // a) * a
    width = -((-(c_hi + 1 - col0)) // a) * a

    def _pad(extent, origin):
        # Quantum must satisfy BOTH constraints: lcm(pad_multiple, a).
        m = math.lcm(pad_multiple, a)
        padded = min(-((-extent) // m) * m, n)
        # Keep inside the global grid by sliding the origin back if needed.
        origin = min(origin, max(0, n - padded))
        return padded, origin

    height, row0 = _pad(height, row0)
    width, col0 = _pad(width, col0)
    win = Window(zoom=zoom, row0=row0, col0=col0, height=height, width=width)
    if align_levels and not win.aligned_to(align_levels):
        raise ValueError(
            f"could not align window to 2^{align_levels} boundaries within "
            f"the z{zoom} grid: {win}"
        )
    return win


#: Windows at or below this cell count route to the Pallas MXU kernel
#: under backend="auto" on TPU: measured flat ~0.33 G pts/s up to
#: 256x256 and 2.6-2.9x over XLA scatter (PERF_NOTES.md); above it the
#: N*H*W MAC term overtakes the scatter cost.
PALLAS_AUTO_MAX_CELLS = 256 * 256

#: The zero constant for Pallas BlockSpec index maps, shared by every
#: kernel module. Must be a CONCRETE int32 (numpy scalar, not jnp —
#: index maps may not capture tracers): under jax_enable_x64 a literal
#: Python 0 traces as int64 and the Mosaic backend fails to legalize
#: the index-map function ("failed to legalize operation 'func.func'",
#: caught on the real chip 2026-07-31 — a stage past what
#: tests/test_lowering.py's jax.export lowering reaches, so only
#: on-chip runs exercise it; that is why this lives in ONE place).
IMAP_ZERO = _np.int32(0)


def _pick_backend(backend: str, window: Window, weighted: bool = False) -> str:
    if backend != "auto":
        return backend
    import jax

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if not on_tpu:
        return "xla"
    if window.height * window.width <= PALLAS_AUTO_MAX_CELLS:
        return "pallas"
    # Large windows: sort-partitioned MXU binning wins big for counts
    # (measured 149 M vs 67 M pts/s on the ~1024x1280 z15 headline
    # window, v5e-1, same session) AND for weighted sums (pair-sorted
    # weights + weight-scaled one-hots: 340.6 ms vs 432.5 ms XLA
    # scatter at the z15 headline window, k=8, v5e-1 round-5 sweep —
    # PERF_NOTES.md round 5).
    return "partitioned"


def bin_rowcol_window(row, col, window: Window, weights=None, valid=None,
                      dtype=None, backend: str = "xla"):
    """Scatter-add pre-projected (row, col) points into a window raster.

    Out-of-window and invalid points are dropped via scatter mode='drop'
    (index -1), the vectorized analog of the reference's filter-by-key
    partitioning. Returns an (H, W) raster.

    ``backend``: "xla" (scatter-add), "pallas" (MXU one-hot matmul
    kernel, TPU only), "partitioned" (sort + per-block MXU kernel for
    LARGE windows, counts and weighted sums; ops/partitioned.py), or
    "auto" (pallas on TPU for windows up to PALLAS_AUTO_MAX_CELLS
    cells). The pallas paths accumulate in f32 — exact for < 2^24
    counts per cell per call — and are cast to the requested ``dtype``.
    """
    if dtype is None:
        dtype = jnp.int32 if weights is None else jnp.float32
    picked = _pick_backend(backend, window, weighted=weights is not None)
    if picked == "partitioned":
        from heatmap_tpu.ops.partitioned import bin_rowcol_window_partitioned

        return bin_rowcol_window_partitioned(
            row, col, window, weights=weights, valid=valid, dtype=dtype
        )
    if picked == "pallas":
        from heatmap_tpu.ops.pallas_kernels import bin_rowcol_window_pallas

        raster = bin_rowcol_window_pallas(
            row, col, window, weights=weights, valid=valid
        )
        return raster.astype(dtype)
    r = jnp.asarray(row, jnp.int32) - window.row0
    c = jnp.asarray(col, jnp.int32) - window.col0
    in_win = (r >= 0) & (r < window.height) & (c >= 0) & (c < window.width)
    if valid is not None:
        in_win = in_win & valid
    # Drop index must be out-of-bounds HIGH: negative indices wrap (JAX
    # normalizes them before the mode="drop" bounds check).
    idx = jnp.where(in_win, r * window.width + c, window.height * window.width)
    w = jnp.ones(idx.shape, dtype) if weights is None else jnp.asarray(weights, dtype)
    flat = jnp.zeros(window.height * window.width, dtype).at[idx].add(w, mode="drop")
    return flat.reshape(window.height, window.width)


def bin_points_window(
    latitude,
    longitude,
    window: Window,
    weights=None,
    valid=None,
    proj_dtype=None,
    dtype=None,
    backend: str = "xla",
):
    """Project lat/lon points and scatter-add them into a window raster.

    ``proj_dtype`` picks the projection precision (mercator.py policy:
    f64 exact when x64 is on, f32 fast otherwise). ``valid`` ANDs with
    the projection validity mask (used e.g. for padding lanes).
    ``backend`` as in bin_rowcol_window.
    """
    row, col, proj_valid = mercator.project_points(
        latitude, longitude, window.zoom, dtype=proj_dtype
    )
    if valid is not None:
        proj_valid = proj_valid & valid
    return bin_rowcol_window(
        row, col, window, weights=weights, valid=proj_valid, dtype=dtype,
        backend=backend,
    )
