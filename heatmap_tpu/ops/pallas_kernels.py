"""Pallas TPU kernels for the hot binning op.

The reference's hot loop is per-record Python trigonometry + string
keys shuffled by Spark (reference heatmap.py:60-75, tile.py:16-21).
The XLA path here (ops.histogram) replaces it with projection +
scatter-add. This module adds a **Pallas MXU formulation** of the
scatter: binning a chunk of points into an (H, W) window is the matmul

    raster += R @ (w * C)      R: (H, N) row one-hot
                               C: (N, W) col one-hot

— a histogram expressed as systolic-array work instead of serialized
scatter updates. One-hots are built in VMEM with ``broadcasted_iota``
comparisons (never materialized in HBM), the raster accumulates in a
VMEM scratch across a sequential grid over point chunks, and a single
HBM write emits the result in the last grid step. Invalid/out-of-window
points are encoded as row=-1, which matches no one-hot row and thus
contributes nothing — branch-free masking.

Cost: N*H*W MACs per N points — ideal for the blob-sized windows the
pipeline actually uses (a 32x32 or 256x256 coarse-tile raster,
reference heatmap.py:16,89 fan-in), where the MXU turns the whole
histogram into a handful of matmul passes; measured 2.6-2.9x faster
than XLA scatter on v5e (PERF_NOTES.md). For very large windows the
one-hot cost grows past the scatter path; ops.histogram stays the
default and callers opt in by calling ``bin_points_window_pallas`` /
``bin_rowcol_window_pallas`` directly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from heatmap_tpu.ops.histogram import IMAP_ZERO, Window
from heatmap_tpu.tilemath import mercator

# Lane-friendly defaults: chunk is a multiple of 128 lanes; 8-row
# sublane alignment comes from H/W being tile multiples in practice.
# 1024 is the measured knee on v5e (smaller chunks under-fill the MXU
# passes; larger ones don't help — the kernel is VPU-bound on one-hot
# construction, ~3x faster than XLA scatter either way).
DEFAULT_CHUNK = 1024


def _histogram_kernel(
    rc_ref, w_ref, out_ref, acc_ref, *, height, width, chunk, precision,
    onehot_dtype
):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    rows = rc_ref[0, :]  # (chunk,) int32, -1 = dropped
    cols = rc_ref[1, :]
    weights = w_ref[0, :]  # (chunk,) f32

    # bf16 one-hots halve the VPU->MXU operand traffic and stay exact:
    # 0 and 1 are representable, and accumulation is f32 regardless
    # (preferred_element_type). Only the *weighted* path needs f32
    # operands, because arbitrary weights don't survive bf16's 8-bit
    # mantissa — the caller picks via onehot_dtype.
    r_ids = jax.lax.broadcasted_iota(jnp.int32, (height, chunk), 0)
    row_onehot = (r_ids == rows[None, :]).astype(onehot_dtype)
    c_ids = jax.lax.broadcasted_iota(jnp.int32, (chunk, width), 1)
    col_onehot = (c_ids == cols[:, None]).astype(onehot_dtype)
    if onehot_dtype == jnp.float32:
        col_onehot = col_onehot * weights[:, None]

    acc_ref[:] += jnp.dot(
        row_onehot,
        col_onehot,
        preferred_element_type=jnp.float32,
        precision=precision,
    )

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(
    jax.jit, static_argnames=("window", "chunk", "interpret", "onehot_dtype")
)
def bin_rowcol_window_pallas(
    row,
    col,
    window: Window,
    weights=None,
    valid=None,
    chunk: int = DEFAULT_CHUNK,
    interpret: bool = False,
    onehot_dtype=None,
):
    """Pallas MXU histogram: pre-projected points -> (H, W) f32 raster.

    Same contract as ops.histogram.bin_rowcol_window (drop out-of-window
    and invalid points) with f32 accumulation — exact for < 2^24 counts
    per cell per call. ``interpret=True`` runs the kernel in interpreter
    mode (CPU tests).
    """
    h, w = window.height, window.width
    r = jnp.asarray(row, jnp.int32) - window.row0
    c = jnp.asarray(col, jnp.int32) - window.col0
    ok = (r >= 0) & (r < h) & (c >= 0) & (c < w)
    if valid is not None:
        ok = ok & valid
    r = jnp.where(ok, r, -1)
    c = jnp.where(ok, c, 0)
    wts = (
        jnp.ones(r.shape, jnp.float32)
        if weights is None
        else jnp.asarray(weights, jnp.float32)
    )
    # Zero dropped points' weights too: row=-1 alone keeps them out of
    # the row one-hot, but a NaN/inf weight would still poison the
    # col-one-hot product (0 * nan = nan).
    wts = jnp.where(ok, wts, 0.0)

    n = r.shape[0]
    n_pad = -(-max(n, 1) // chunk) * chunk
    if n_pad != n:
        pad = n_pad - n
        r = jnp.concatenate([r, jnp.full(pad, -1, jnp.int32)])
        c = jnp.concatenate([c, jnp.zeros(pad, jnp.int32)])
        wts = jnp.concatenate([wts, jnp.zeros(pad, jnp.float32)])
    rc = jnp.stack([r, c])  # (2, n_pad)
    wts = wts[None, :]  # (1, n_pad)

    # 0/1 one-hots and unit weights are exact in the MXU's default
    # bf16 passes; arbitrary weights need full f32 precision or the
    # TPU matmul rounds them to 8 mantissa bits. The count path goes
    # further and feeds bf16 one-hot *operands* (half the VPU->MXU
    # traffic, still exact — counts accumulate in f32).
    precision = (
        jax.lax.Precision.DEFAULT if weights is None
        else jax.lax.Precision.HIGHEST
    )
    if onehot_dtype is None:
        onehot_dtype = jnp.bfloat16 if weights is None else jnp.float32
    elif weights is not None and onehot_dtype != jnp.float32:
        raise ValueError(
            "weighted binning requires f32 one-hots (bf16 would round "
            "the weights); leave onehot_dtype unset"
        )
    kernel = functools.partial(
        _histogram_kernel, height=h, width=w, chunk=chunk,
        precision=precision, onehot_dtype=onehot_dtype,
    )
    z = IMAP_ZERO  # concrete int32; see histogram.IMAP_ZERO
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((h, w), jnp.float32),
        grid=(n_pad // chunk,),
        in_specs=[
            pl.BlockSpec((2, chunk), lambda i: (z, i)),
            pl.BlockSpec((1, chunk), lambda i: (z, i)),
        ],
        out_specs=pl.BlockSpec((h, w), lambda i: (z, z)),
        scratch_shapes=[pltpu_vmem((h, w), jnp.float32)],
        interpret=interpret,
    )(rc, wts)


def pltpu_vmem(shape, dtype):
    """VMEM scratch constructor, importable lazily so CPU-only installs
    without the TPU plugin still import this module."""
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


def bin_points_window_pallas(
    latitude,
    longitude,
    window: Window,
    weights=None,
    valid=None,
    proj_dtype=None,
    chunk: int = DEFAULT_CHUNK,
    interpret: bool = False,
):
    """Fused projection + Pallas MXU histogram (bin_points_window's
    opt-in fast path)."""
    rowf, colf, proj_valid = mercator.project_points(
        latitude, longitude, window.zoom, dtype=proj_dtype
    )
    if valid is not None:
        proj_valid = proj_valid & valid
    return bin_rowcol_window_pallas(
        rowf, colf, window,
        weights=weights, valid=proj_valid, chunk=chunk, interpret=interpret,
    )
