"""Sort-partitioned MXU binning for LARGE windows.

The whole-window Pallas histogram (pallas_kernels.py) pays H*W MACs per
point, so it only wins for blob-sized windows; the XLA scatter path
pays a flat ~10-30 ns *per update* on v5e (PERF_NOTES.md), which is the
headline-bench bottleneck for big rasters (a z15 metro window is ~1.3M
cells). This module restores MXU locality for big windows:

1. project to linear cell indices and **sort** (XLA's comparison sort
   is the one fast reshuffling primitive on this chip);
2. cut the sorted stream into fixed chunks; a chunk whose cells all
   land in one aligned ``block_cells`` region is **good** — after
   sorting, that's the common case for clustered GPS data;
3. chunk block ids are non-decreasing in place (the stream is sorted),
   so no reorder pass is needed — bad chunks are simply masked;
4. a Pallas kernel walks the good chunks with a scalar-prefetched
   output-block index per chunk (bases are monotone by construction,
   so each output block's visits are consecutive): each chunk becomes
   a side x side one-hot matmul into its block — the same MXU
   formulation as the small-window kernel, but against one aligned
   ``block_cells``-cell block instead of the whole raster;
5. the bad chunks (sparse fringes, block-straddlers, padding) are
   gathered by row and go through the ordinary scatter, bounded to
   1/``bad_frac`` of the points instead of the full stream;
6. if an adversarial distribution makes more than that fraction of
   chunks bad, ``lax.cond`` falls back to the plain full scatter —
   correctness never depends on the data being friendly.

Counts accumulate in f32 inside the kernel (exact < 2^24 per cell per
call) and int32 on the scatter tail; the merged raster is returned in
the requested dtype.

Weighted binning (BASELINE.md config 3) rides the same machinery: the
sort carries the weight as a ``lax.sort`` payload operand (XLA's sort
permutes payloads in-pass — no separate gather, which costs as much as
the scatter being avoided, PERF_NOTES.md), and the per-chunk matmul
scales the column one-hot by the weight, so each good chunk is
``row_onehot @ (col_onehot * w)``. Weighted sums accumulate in f32:
bit-exact vs the scatter path for integer-valued weights with per-cell
sums < 2^24 (the oracle-testable contract), within f32 rounding
otherwise (summation order differs from the scatter path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from heatmap_tpu.ops.histogram import IMAP_ZERO, Window

DEFAULT_CHUNK = 1024
#: Independently sorted rows per call (1 = one flat sort). 8 is the
#: measured on-chip default (sweep 2026-07-31, v5e-1, 33.5M points,
#: headline window): streams=8/32 run the full binning in ~197 ms vs
#: ~403 ms for the flat sort — 2.0x — and are bit-exact in all verify
#: cases. The isolated sort-shape probe shows the row sort itself is
#: only ~8% faster, so most of the win is the per-stream slab
#: accumulation pipelining the pallas grid better than one giant
#: visit-run sequence. streams=8 over 32: same speed, fewer slabs
#: (less zero-padding and a smaller output-blocks buffer).
DEFAULT_STREAMS = 8

#: Cap on the summed per-stream output-slab footprint (bytes). Each
#: stream accumulates its own (n_blocks * block_cells) f32 slab, so
#: streams multiplies output memory x8 by default; a window near the
#: int32 cell-id cap (~8 GiB of cells) fits HBM at streams=1 but not
#: x8. 4 GiB leaves the measured headline configs (z15 window, 256 MiB
#: slab -> 16 streams allowed) untouched while clamping the giant-
#: window tail down to what fits.
STREAM_SLAB_BUDGET = 4 << 30
#: Cells per aligned output block (a side x side one-hot factor pair).
#: Smaller blocks cut the per-point one-hot construction (VPU, 2*side
#: compares+casts per point) and the MXU MACs quadratically, at the
#: price of a lower good-chunk rate on dispersed data (a chunk must
#: land inside ONE aligned block). 2^16 = 256x256 is the round-1
#: measured default; sweep block_cells on-chip before changing it.
DEFAULT_BLOCK_CELLS = 1 << 16


def masked_local_rc(block_start, good, stream, block_cells, side):
    """(row-in-block, col-in-block) for one chunk's sorted cell ids,
    with dropped lanes as row=-1/col=0 (matching no one-hot row).

    Shared by every partitioned-MXU kernel (count / weighted /
    multi-channel segment). Every constant is explicitly int32: under
    ``jax_enable_x64`` (the batch job's z21 precision policy) weak
    Python-int literals trace as int64 scalars inside Pallas kernels,
    and Mosaic's int64->int32 convert lowering recurses forever
    (RecursionError caught by the on-chip verify tool 2026-07-31;
    pinned by tests/test_lowering.py)."""
    bc = jnp.int32(block_cells)
    sd = jnp.int32(side)
    local = stream - block_start * bc
    ok = (good == jnp.int32(1)) & (local >= jnp.int32(0)) & (local < bc)
    rloc = jnp.where(ok, local // sd, jnp.int32(-1))
    cloc = jnp.where(ok, local % sd, jnp.int32(0))
    return rloc, cloc


def _partition_kernel(base_ref, good_ref, first_ref, last_ref, s_ref,
                      zeros_ref, out_ref, acc_ref, *, chunk, block_cells,
                      side, n_blocks):
    # Count path (weighted binning goes through the separate
    # _partition_kernel_weighted twin); zeros_ref only alias-inits the
    # output.
    del zeros_ref
    i = pl.program_id(0)

    @pl.when(first_ref[i] == 1)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # base_ref holds FLAT output-slab ids stream*n_blocks + block; the
    # cell offset inside the window depends only on the block part.
    rloc, cloc = masked_local_rc(
        base_ref[i] % jnp.int32(n_blocks), good_ref[i], s_ref[0, 0, :],
        block_cells, side,
    )

    r_ids = lax.broadcasted_iota(jnp.int32, (side, chunk), 0)
    c_ids = lax.broadcasted_iota(jnp.int32, (chunk, side), 1)
    row_onehot = (r_ids == rloc[None, :]).astype(jnp.bfloat16)
    col_onehot = (c_ids == cloc[:, None]).astype(jnp.bfloat16)
    acc_ref[0] += jnp.dot(
        row_onehot, col_onehot, preferred_element_type=jnp.float32
    )

    @pl.when(last_ref[i] == 1)
    def _():
        out_ref[:] = acc_ref[:]


def _partition_kernel_weighted(base_ref, good_ref, first_ref, last_ref,
                               s_ref, w_ref, zeros_ref, out_ref, acc_ref, *,
                               chunk, block_cells, side, n_blocks):
    """Weighted twin of :func:`_partition_kernel` (kept as a SEPARATE
    kernel, not a kwarg branch, so the count path stays byte-stable):
    the column one-hot is scaled by the point's weight, making each
    chunk's contribution ``row_onehot @ (col_onehot * w)``. Masked /
    out-of-block lanes zero out through the all-false one-hot row."""
    del zeros_ref
    i = pl.program_id(0)

    @pl.when(first_ref[i] == 1)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    rloc, cloc = masked_local_rc(
        base_ref[i] % jnp.int32(n_blocks), good_ref[i], s_ref[0, 0, :],
        block_cells, side,
    )

    r_ids = lax.broadcasted_iota(jnp.int32, (side, chunk), 0)
    c_ids = lax.broadcasted_iota(jnp.int32, (chunk, side), 1)
    # f32 one-hots here: the weight factor makes bf16 lossy (weights are
    # arbitrary f32), and the f32/bf16 gap measured ~0 at >= 256x256.
    row_onehot = (r_ids == rloc[None, :]).astype(jnp.float32)
    col_w = (c_ids == cloc[:, None]).astype(jnp.float32) * w_ref[0, 0, :][:, None]
    # HIGHEST: the default f32 matmul may execute as one bf16 pass on
    # the MXU (8 mantissa bits), which would round the weights — the
    # same contract as the small-window kernel (pallas_kernels.py).
    acc_ref[0] += jnp.dot(
        row_onehot, col_w, preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )

    @pl.when(last_ref[i] == 1)
    def _():
        out_ref[:] = acc_ref[:]


def _partitioned_path(s2, good2, n_blocks, hw, chunk,
                      bad_cap_chunks, interpret, block_cells, side,
                      w2=None):
    """Good chunks -> pallas blocks; bad tail -> bounded scatter.

    ``s2`` is (streams, L): each row independently sorted (one flat
    sort is the streams=1 case). ``good2`` is the per-(stream, chunk)
    mask computed by the caller — the SAME mask that sized the bounded
    tail via n_bad, so the tail provably covers every chunk this path
    masks out. Each stream accumulates into its own slab of output
    blocks; the slabs sum at the end (counts are linear), which keeps
    every output block's visits consecutive WITHIN the flattened grid
    without any cross-stream ordering requirement.

    ``w2`` (same shape as ``s2``, f32, already permuted by the caller's
    pair sort) switches to the weighted kernel and a weighted f32
    scatter tail.
    """
    streams, L = s2.shape
    nck = L // chunk
    n_chunks = streams * nck
    first = s2[:, ::chunk]
    fblk = first // block_cells

    # Each row is sorted, so chunk block ids are non-decreasing within
    # a stream — no reorder pass over the point stream is needed.
    # Forward-fill bad chunks with the last good base per stream
    # (cummax works because good bases are non-decreasing); leading
    # bads clamp to block 0, fully masked; a bad chunk between two
    # blocks joins the previous block's visit run and writes nothing.
    base2 = jnp.maximum(
        lax.cummax(jnp.where(good2, fblk, -1), axis=1), 0
    )
    # Flat output-slab id: stream*n_blocks + block. Monotone within a
    # stream and strictly increasing across stream boundaries' slabs,
    # so visit runs stay consecutive over the flattened grid.
    ob = (
        jnp.arange(streams, dtype=base2.dtype)[:, None] * n_blocks + base2
    ).reshape(-1)
    good = good2.reshape(-1)
    gi = good.astype(jnp.int32)
    first_visit = jnp.concatenate(
        [jnp.ones(1, jnp.int32),
         (ob[1:] != ob[:-1]).astype(jnp.int32)]
    )
    last_visit = jnp.concatenate(
        [(ob[1:] != ob[:-1]).astype(jnp.int32),
         jnp.ones(1, jnp.int32)]
    )

    from jax.experimental.pallas import tpu as pltpu

    # (n_chunks, 1, chunk) so the last-two block dims (1, chunk)
    # satisfy the TPU tiling rule: sublane block == array dim
    # (1 == 1), lane block divisible by 128.  A flat
    # (n_chunks, chunk) array with block (1, chunk) is rejected
    # by Mosaic (sublane 1 neither 8-divisible nor full).
    z = IMAP_ZERO  # concrete int32; see histogram.IMAP_ZERO
    stream_spec = pl.BlockSpec((1, 1, chunk), lambda i, *_: (i, z, z))
    block_spec = pl.BlockSpec(
        (1, side, side), lambda i, base, *_: (base[i], z, z)
    )
    weighted = w2 is not None
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(n_chunks,),
        in_specs=(
            [stream_spec, stream_spec, block_spec] if weighted
            else [stream_spec, block_spec]
        ),
        out_specs=block_spec,
        scratch_shapes=[pltpu.VMEM((1, side, side), jnp.float32)],
    )
    kernel = _partition_kernel_weighted if weighted else _partition_kernel
    zeros = jnp.zeros((streams * n_blocks, side, side), jnp.float32)
    operands = [ob, gi, first_visit, last_visit,
                s2.reshape(n_chunks, 1, chunk)]
    if weighted:
        operands.append(w2.reshape(n_chunks, 1, chunk))
    operands.append(zeros)
    blocks = pl.pallas_call(
        functools.partial(kernel, chunk=chunk,
                          block_cells=block_cells, side=side,
                          n_blocks=n_blocks),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (streams * n_blocks, side, side), jnp.float32
        ),
        # zeros operand -> output (position counts the scalar prefetches)
        input_output_aliases={6 if weighted else 5: 0},
        interpret=interpret,
    )(*operands)
    dense = (
        blocks.reshape(streams, n_blocks * block_cells).sum(axis=0)[:hw]
        if streams > 1
        else blocks.reshape(n_blocks * block_cells)[:hw]
    )

    # Bounded scatter over the bad chunks only: gather exactly their
    # rows (the cond guarantees there are at most bad_cap_chunks of
    # them, so the fixed-size nonzero captures ALL of them); the
    # fill rows read as sentinel, and sentinel/out-of-range cells drop
    # in the scatter, so no weight masking is needed.
    bad_idx = jnp.nonzero(~good, size=bad_cap_chunks,
                          fill_value=n_chunks)[0]
    bad_rows = jnp.take(
        s2.reshape(n_chunks, chunk), bad_idx, axis=0,
        mode="fill", fill_value=hw,
    )
    if weighted:
        bad_w = jnp.take(
            w2.reshape(n_chunks, chunk), bad_idx, axis=0,
            mode="fill", fill_value=0.0,
        )
        tail = (
            jnp.zeros(hw, jnp.float32)
            .at[bad_rows.reshape(-1)]
            .add(bad_w.reshape(-1), mode="drop")
        )
        return dense + tail
    tail = (
        jnp.zeros(hw, jnp.int32)
        .at[bad_rows.reshape(-1)]
        .add(1, mode="drop")
    )
    return dense.astype(jnp.int32) + tail


def clamp_streams(streams: int, window: Window,
                  block_cells: int = DEFAULT_BLOCK_CELLS) -> int:
    """Largest stream count <= ``streams`` whose summed output slabs
    fit STREAM_SLAB_BUDGET for this window (always >= 1)."""
    hw = window.height * window.width
    slab_bytes = -(-hw // block_cells) * block_cells * 4
    return max(1, min(streams, STREAM_SLAB_BUDGET // max(slab_bytes, 1)))


def bin_rowcol_window_partitioned(
    row,
    col,
    window: Window,
    weights=None,
    valid=None,
    chunk: int = DEFAULT_CHUNK,
    bad_frac: int = 128,
    interpret: bool | None = None,
    dtype=None,
    block_cells: int = DEFAULT_BLOCK_CELLS,
    streams: int = DEFAULT_STREAMS,
):
    """Sort-partitioned binning of pre-projected points into a large window.

    Contract matches ops.histogram.bin_rowcol_window: out-of-window /
    invalid points drop. ``weights=None`` counts occurrences (int32,
    bit-exact vs the scatter path); ``weights`` given sums them in f32
    (bit-exact vs scatter for integer-valued weights with per-cell sums
    < 2^24, within f32 rounding otherwise — the pair sort changes
    summation order). ``bad_frac``: the scatter tail is sized
    n/bad_frac points; distributions badder than that fall back to the
    full scatter inside the same jit (lax.cond). The 128 default is
    the round-5 on-chip sweep winner (151.2 ms vs 189.2 ms at bf=8 on
    the z15 headline window, v5e-1 — 222.0 M pts/s; PERF_NOTES.md
    round 5): the tail rarely fills, so a smaller bound frees HBM and
    scatter work without changing results. ``interpret`` defaults
    to True on CPU (pallas has no compiled CPU lowering), False on
    accelerators. ``block_cells`` sets the aligned output-block size
    (must be an even power of two >= 2^12 so the side is a
    lane-friendly square; see DEFAULT_BLOCK_CELLS). ``streams`` splits
    the cell-id stream into that many independently sorted rows (one
    batched row sort instead of one flat sort; each row can be
    VMEM-resident), each accumulating its own output-block slab, summed
    at the end — same raster bit-for-bit, different sort-cost/memory
    tradeoff. streams=1 is the flat-sort baseline.

    ``streams`` is clamped so the summed per-stream output slabs
    (streams * n_blocks * block_cells f32, ~32 B/cell at the x8
    default) stay under STREAM_SLAB_BUDGET: windows near the int32
    cell-id cap fit HBM at streams=1 and must not OOM just because
    backend="auto" routed here with the streams default.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    if dtype is None:
        dtype = jnp.int32 if weights is None else jnp.float32
    streams = clamp_streams(streams, window, block_cells)
    return _bin_partitioned_jit(
        row, col, window, weights, valid, chunk=chunk, bad_frac=bad_frac,
        interpret=interpret, dtype=dtype, block_cells=block_cells,
        streams=streams,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "window", "chunk", "bad_frac", "interpret", "dtype", "block_cells",
        "streams",
    ),
)
def _bin_partitioned_jit(
    row,
    col,
    window: Window,
    weights=None,
    valid=None,
    chunk: int = DEFAULT_CHUNK,
    bad_frac: int = 128,
    interpret: bool = False,
    dtype=jnp.int32,
    block_cells: int = DEFAULT_BLOCK_CELLS,
    streams: int = 1,
):
    h, w = window.height, window.width
    hw = h * w
    if hw >= (1 << 31):
        raise ValueError(f"window too large for int32 cell ids: {window}")
    side = 1 << (block_cells.bit_length() // 2)
    if side * side != block_cells or side < 64:
        raise ValueError(
            f"block_cells must be an even power of two >= 4096 "
            f"(a square side of >= 64 lanes), got {block_cells}"
        )
    if streams < 1:
        raise ValueError(f"streams must be >= 1, got {streams}")
    n_blocks = -(-hw // block_cells)
    sentinel = n_blocks * block_cells  # beyond every block, drops everywhere

    r = jnp.asarray(row, jnp.int32) - window.row0
    c = jnp.asarray(col, jnp.int32) - window.col0
    ok = (r >= 0) & (r < h) & (c >= 0) & (c < w)
    if valid is not None:
        ok = ok & valid
    idx = jnp.where(ok, r * w + c, sentinel)
    weighted = weights is not None
    if weighted:
        # Dropped lanes carry weight 0 as well as the sentinel cell id,
        # so every downstream path (matmul mask, bounded tail, full-
        # scatter fallback) is doubly safe.
        wts = jnp.where(ok, jnp.asarray(weights, jnp.float32), 0.0)

    n = idx.shape[0]
    # Pad so each of the `streams` rows is a whole number of chunks.
    per_stream = -(-max(n, 1) // (streams * chunk)) * chunk
    n_pad = streams * per_stream
    if n_pad != n:
        idx = jnp.concatenate(
            [idx, jnp.full(n_pad - n, sentinel, jnp.int32)]
        )
        if weighted:
            wts = jnp.concatenate([wts, jnp.zeros(n_pad - n, jnp.float32)])
    n_chunks = n_pad // chunk
    # Padding sentinels land in the trailing rows and sort to each
    # row's end, so they can mark up to ~streams extra chunks bad on
    # top of the data-dependent ones.
    bad_cap_chunks = max(streams + 1, n_chunks // bad_frac)

    # Unstable sort: for counts, cell ids are the only payload, so equal
    # keys are indistinguishable and stability would only cost time.
    # Weighted, the weight rides as a lax.sort payload operand — XLA
    # permutes it in-pass, avoiding the separate gather that costs as
    # much as the scatter being avoided (PERF_NOTES.md). With
    # streams > 1 this is one batched row sort (axis -1).
    if weighted:
        s2, w2 = lax.sort(
            (idx.reshape(streams, per_stream),
             wts.reshape(streams, per_stream)),
            dimension=1, num_keys=1, is_stable=False,
        )
    else:
        s2 = jnp.sort(idx.reshape(streams, per_stream), axis=-1, stable=False)
        w2 = None
    # The single source of truth for chunk goodness: fully inside one
    # aligned block AND free of sentinels. The bounded tail in
    # _partitioned_path covers exactly the chunks this marks bad, and
    # the cond below guarantees they fit.
    first = s2[:, ::chunk]
    last = s2[:, chunk - 1 :: chunk]
    good2 = (first // block_cells == last // block_cells) & (last < sentinel)
    n_bad = (~good2).sum()

    if weighted:
        raster = lax.cond(
            n_bad <= bad_cap_chunks,
            lambda s_, ww_, good_: _partitioned_path(
                s_, good_, n_blocks, hw, chunk, bad_cap_chunks,
                interpret, block_cells, side, w2=ww_,
            ),
            lambda s_, ww_, good_: (
                jnp.zeros(hw, jnp.float32)
                .at[s_.reshape(-1)]
                .add(ww_.reshape(-1), mode="drop")
            ),
            s2,
            w2,
            good2,
        )
    else:
        raster = lax.cond(
            n_bad <= bad_cap_chunks,
            lambda s_, good_: _partitioned_path(
                s_, good_, n_blocks, hw, chunk, bad_cap_chunks,
                interpret, block_cells, side,
            ),
            lambda s_, good_: (
                jnp.zeros(hw, jnp.int32).at[s_.reshape(-1)].add(1, mode="drop")
            ),
            s2,
            good2,
        )
    return raster.reshape(h, w).astype(dtype)
