"""Fixed-capacity sparse aggregation: sort + segment-sum over integer keys.

The XLA-native replacement for ``reduceByKey`` (reference
heatmap.py:111): instead of a hash-partitioned shuffle, keys are sorted
on-device and reduced with a single segment scatter-add. Everything is
static-shaped (capacity chosen ahead of time, SURVEY.md §7 hard part (c)
"dynamic occupancy"), so the whole thing lives happily under ``jit``.

Scatter-add on TPU is historically slow for random indices; sorting
first turns the scatter into (mostly) sequential segment writes, which
is the TPU-friendly shape of this computation.
"""

from __future__ import annotations

import jax.numpy as jnp

from heatmap_tpu.utils import trace


def _sentinel_for(dtype):
    return jnp.iinfo(jnp.dtype(dtype)).max


def aggregate_keys(keys, weights=None, valid=None, capacity=None, acc_dtype=None):
    """Reduce-by-key: sum ``weights`` per unique key.

    Args:
      keys: int array [N]. Any integer dtype, EXCEPT that the dtype's
        maximum value is reserved as the internal sentinel — a key equal
        to ``iinfo(dtype).max`` would be silently dropped. All tile-key
        encodings in this framework stay well below it (int32 Morton
        codes <= 2^31-2 at z15, packed int64 keys use 58 bits), so this
        only matters for caller-invented key schemes.
      weights: [N] or None (None counts occurrences in int32).
      valid: optional bool [N]; invalid lanes are excluded entirely.
      capacity: max distinct keys to emit (default N). Distinct keys
        beyond capacity are silently dropped — callers size capacity for
        their data (e.g. number of occupied tiles).
      acc_dtype: accumulator dtype (int32 for counts, f32 for weights).

    Returns:
      (unique_keys[capacity], sums[capacity], n_unique) — slots past
      n_unique hold sentinel key (intmax) and zero sum. unique_keys are
      sorted ascending, which downstream pyramid levels rely on.

      ``n_unique`` is the TRUE distinct-key count and can exceed
      ``capacity``: that is the overflow signal, meaning the largest
      ``n_unique - capacity`` keys were dropped and sums no longer total
      the input. Callers must slice with ``uniq[:min(n, capacity)]`` (or
      size capacity generously and treat ``n > capacity`` as an error).
    """
    keys = jnp.asarray(keys)
    n = keys.shape[0]
    capacity = n if capacity is None else capacity
    if acc_dtype is None:
        acc_dtype = jnp.int32 if weights is None else jnp.float32
    w = (
        jnp.ones(n, acc_dtype)
        if weights is None
        else jnp.asarray(weights, acc_dtype)
    )
    sentinel = _sentinel_for(keys.dtype)
    if valid is not None:
        keys = jnp.where(valid, keys, sentinel)
        w = jnp.where(valid, w, 0)

    # Counts (uniform weights) are exact under any summation order, so
    # the sort can be unstable; float weights keep the stable order so
    # results are reproducible against host-order oracles bit-for-bit.
    with trace.stage_span("cascade.sort", items=n):
        order = jnp.argsort(keys, stable=weights is not None)
        sk, sw = trace.stage_block((keys[order], w[order]))
    with trace.stage_span("cascade.segment-reduce", items=n):
        return trace.stage_block(
            aggregate_sorted_keys(sk, sw, capacity, sentinel=sentinel))


def aggregate_sorted_keys(sorted_keys, sorted_weights, capacity, sentinel=None):
    """Segment-sum already-sorted keys (see :func:`aggregate_keys`).

    Separated out because the Morton pyramid re-aggregates the *same*
    sorted order at every level (ops/pyramid.py) — sort once, reduce L
    times.
    """
    if sentinel is None:
        sentinel = _sentinel_for(sorted_keys.dtype)
    first = jnp.concatenate(
        [
            jnp.ones((1,), bool),
            sorted_keys[1:] != sorted_keys[:-1],
        ]
    )
    # Sentinel lanes (masked-out points) must not open a segment.
    is_real = sorted_keys != sentinel
    first = first & is_real
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    # Drop index must be out-of-bounds HIGH (capacity): negative indices
    # wrap before the mode="drop" bounds check.
    seg = jnp.where(is_real, seg, capacity)

    sums = jnp.zeros((capacity,), sorted_weights.dtype).at[seg].add(
        sorted_weights, mode="drop"
    )
    unique = (
        jnp.full((capacity,), sentinel, sorted_keys.dtype)
        .at[seg]
        .set(sorted_keys, mode="drop")
    )
    n_unique = jnp.sum(first.astype(jnp.int32))
    return unique, sums, n_unique
