"""Zoom-pyramid rollups: reshape-sums (dense) and Morton shifts (sparse).

``pyramid_sparse_morton`` is the scatter-based production path;
``pyramid_sparse_morton_partitioned`` is the count-only MXU
reformulation (ops/sparse_partitioned.py) that reduces EVERY level
from the original sorted point stream under ``key >> 2i`` — unit
weights at every level, which is what keeps the slab-wise f32
accumulation exact (re-aggregating a previous level's counts as
weights would overflow the f32 slab bound). Pending on-chip
measurement before any routing (PERF_NOTES.md).

The reference coarsens one zoom per Spark stage by round-tripping every
aggregate through inverse+forward projection (reference heatmap.py:60-61,
109-117) — 15 redundant trig passes and 32 shuffles. With integer tile
keys the parent relation is a bit shift (tilemath/keys.py), so:

- dense: a full pyramid from a window raster is a chain of 2x2
  reshape+sums, entirely on-device, zero trig;
- sparse: Morton codes sorted once at detail zoom stay sorted under the
  ``>> 2`` parent shift, so every coarser level is a plain segment-sum
  over the already-sorted order (ops/sparse.py).

Equivalence to the reference's center-re-projection is property-tested
in tests/test_keys.py::test_parent_equals_reference_center_reprojection.
"""

from __future__ import annotations

import jax.numpy as jnp

from heatmap_tpu.ops import sparse as sparse_ops
from heatmap_tpu.utils import trace


def coarsen_raster(raster):
    """Sum 2x2 blocks: (..., H, W) -> (..., H//2, W//2).

    Requires even H, W (Window.aligned_to guarantees this for aligned
    windows).
    """
    *batch, h, w = raster.shape
    if h % 2 or w % 2:
        raise ValueError(f"raster {raster.shape} not 2x2-coarsenable")
    r = raster.reshape(*batch, h // 2, 2, w // 2, 2)
    return r.sum(axis=(-3, -1))


def pyramid_from_raster(raster, levels: int):
    """Full rollup: returns [raster, coarsen(raster), ...] — levels+1 entries.

    The i-th entry is the detail raster coarsened i zooms; with an
    aligned Window the entry at level i covers rows
    [row0>>i, (row0+H)>>i) of the global grid at zoom-i.
    """
    out = [raster]
    for _ in range(levels):
        raster = coarsen_raster(raster)
        out.append(raster)
    return out


def _level_caps(capacity, n: int, levels: int) -> list:
    """Normalize the per-level capacity spec (int / None / list)."""
    caps = (
        [capacity or n] * (levels + 1)
        if capacity is None or isinstance(capacity, int)
        else list(capacity)
    )
    if len(caps) != levels + 1:
        raise ValueError(f"need {levels + 1} capacities, got {len(caps)}")
    return caps


def pyramid_sparse_morton(
    codes,
    weights=None,
    valid=None,
    levels: int = 0,
    capacity=None,
    acc_dtype=None,
    adaptive: bool = False,
):
    """Sparse pyramid: per-level (unique Morton codes, sums) from point codes.

    Sorts once at detail zoom, then re-reduces the shifted (still
    sorted) codes per level. Levels beyond the first operate on the
    previous level's unique codes (capacity-sized), not the raw points,
    so total work is O(N log N + N + levels * capacity).

    Returns a list of (codes[capacity_i], sums[capacity_i], n_unique),
    entry 0 at detail zoom, entry i coarsened by i zooms.
    ``capacity`` may be an int (same for all levels) or a per-level list.

    ``adaptive=True`` (EAGER callers only — it reads each level's true
    unique count from the device) shrinks every subsequent level's
    arrays to the next power of two above the previous level's unique
    count. Per-level reductions cost two ~8-30 ns/element scatters on
    TPU (PERF_NOTES.md), so on collapsing data this turns
    ``levels * capacity`` scatter work into ~``2-3 * n_unique_0`` —
    results are identical (the dropped slots are sentinel padding).
    Under jit the counts are tracers and this flag must stay False.
    """
    codes = jnp.asarray(codes)
    n = codes.shape[0]
    caps = _level_caps(capacity, n, levels)

    out = []
    uniq, sums, count = sparse_ops.aggregate_keys(
        codes, weights=weights, valid=valid, capacity=caps[0], acc_dtype=acc_dtype
    )
    out.append((uniq, sums, count))
    sentinel = jnp.iinfo(codes.dtype).max
    for lvl in range(1, levels + 1):
        if adaptive:
            # One scalar sync per level; slots past n_unique are pure
            # sentinel padding, so the slice changes nothing but the
            # amount of padding the next reduction drags through HBM.
            # The INPUT slice must never go below n_real (dropping real
            # aggregates pre-reduction would falsify the unique count
            # that overflow detection relies on) — a caller-configured
            # caps[lvl] smaller than that bounds only the OUTPUT below,
            # where n_unique > capacity stays detectable. An overflowed
            # previous level (n_real > its array) skips shrinking.
            n_real = int(count)
            if n_real <= uniq.shape[0]:
                keep = max(64, 1 << max(0, n_real - 1).bit_length())
                if keep < uniq.shape[0]:
                    uniq = uniq[:keep]
                    sums = sums[:keep]
        # Parent codes of the previous level's uniques; sentinel slots
        # must stay sentinel (a plain shift would corrupt them into
        # plausible-looking codes).
        parents = jnp.where(uniq == sentinel, sentinel, uniq >> 2)
        with trace.stage_span("cascade.segment-reduce",
                              items=int(uniq.shape[0])):
            uniq, sums, count = trace.stage_block(
                sparse_ops.aggregate_sorted_keys(
                    parents, sums, min(caps[lvl], uniq.shape[0]) if adaptive
                    else caps[lvl],
                    sentinel=sentinel,
                ))
        out.append((uniq, sums, count))
    return out


def pyramid_sparse_morton_partitioned(
    codes,
    valid=None,
    levels: int = 0,
    capacity=None,
    chunk: int | None = None,
    block_cells: int | None = None,
    slab: int | None = None,
    interpret: bool | None = None,
    streams: int = 1,
    weights=None,
    weight_bound: int | None = None,
):
    """Sparse pyramid on the multi-channel MXU reduction.

    Same contract as :func:`pyramid_sparse_morton` (keys int64 with
    int64-max sentinel padding, per-level capacities), but every level
    is reduced from the ORIGINAL sorted stream shifted by ``2*level``
    — one sort, then ``levels+1`` kernel passes that replace the 2
    scatters per level (ops/sparse_partitioned.py rationale). Counts
    (``weights=None``, int32 sums) or bounded-integer weights
    (``weights`` + static ``weight_bound``: integers in
    [0, weight_bound], f64 sums, exactness via the shrunk slab;
    violations poison n_unique — see
    sparse_partitioned.aggregate_sorted_keys_partitioned). Fractional
    weights stay on the scatter pyramid. Keys must fit 60 bits.
    Tunables default to sparse_partitioned's DEFAULT_* values.
    """
    from heatmap_tpu.ops import sparse_partitioned as sp

    chunk = sp.DEFAULT_CHUNK if chunk is None else chunk
    block_cells = sp.DEFAULT_BLOCK_CELLS if block_cells is None else block_cells
    slab = sp.DEFAULT_SLAB if slab is None else slab

    codes = jnp.asarray(codes)
    if codes.dtype != jnp.int64:
        codes = codes.astype(jnp.int64)
    n = codes.shape[0]
    caps = _level_caps(capacity, n, levels)

    sentinel = jnp.iinfo(jnp.int64).max
    keys = codes if valid is None else jnp.where(valid, codes, sentinel)
    with trace.stage_span("cascade.sort", items=n):
        if weights is None:
            skeys = trace.stage_block(jnp.sort(keys, stable=False))
            sw = None
        else:
            # Weights ride the same order as their keys (integer sums
            # are order-free, so the unstable argsort is fine).
            order = jnp.argsort(keys, stable=False)
            skeys = keys[order]
            sw = trace.stage_block(jnp.asarray(weights)[order])

    out = []
    for lvl in range(levels + 1):
        # Right shifts preserve the sort; the shifted sentinel
        # (intmax >> 2*lvl) still exceeds every real (< 2^60) key at
        # the shifted width, so it keeps sorting last and masking out.
        with trace.stage_span("cascade.segment-reduce", items=n):
            uniq, sums, n_unique = trace.stage_block(
                sp.aggregate_sorted_keys_partitioned(
                    skeys >> (2 * lvl),
                    caps[lvl],
                    sentinel=sentinel >> (2 * lvl),
                    chunk=chunk,
                    block_cells=block_cells,
                    slab=slab,
                    interpret=interpret,
                    streams=streams,
                    sorted_weights=sw,
                    weight_bound=weight_bound,
                ))
        # Normalize padding to the repo-wide int64-max sentinel (the
        # per-level call pads with its SHIFTED sentinel, which a
        # `uniq != intmax` consumer mask would let through as phantom
        # zero-count cells). The kernel already sentinels zero-sum
        # segments via its presence channel, so masking on the sums
        # here would be wrong for weighted zero totals — mask on the
        # SHIFTED sentinel instead.
        uniq = jnp.where(uniq == (sentinel >> (2 * lvl)), sentinel, uniq)
        out.append((uniq, sums, n_unique))
    return out
