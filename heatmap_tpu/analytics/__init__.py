"""Integral-histogram pyramids and an O(1) range-query engine.

Tiles answer "render this 256x256 square"; production users ask "how
many points in this drawn bbox, and where are the top-k hotspots?".
Following the integral-histogram construction (arxiv 1711.01919), this
package materializes a summed-area table per (user, timespan) pair per
coarse level on the batch/delta cascade path, so any axis-aligned
rectangle sum is four corner lookups — with top-k-hotspot and quantile
queries built on the same pyramid by pruned coarse-to-fine descent.

- integral.py  SAT build twins (jit'd JAX scan for the cascade path,
               numpy for serving), Morton-shard merge by linearity,
               integral-z*.npz artifact read/write/verify.
- query.py     numpy-only evaluators: range_sum / top_k_hotspots /
               quantile, each with an exact row-scan fall-through.
- metrics.py   obs registry handles (docs/observability.md).

Import discipline: everything importable from here is numpy-only; jax
loads lazily inside the ``*_jax`` functions (tests/test_obs.py greps).
"""

from heatmap_tpu.analytics.integral import (  # noqa: F401
    DEFAULT_MAX_Z, HARD_MAX_Z, SCHEMA, IntegralPair, build_pair,
    grid_from_sat, integral2d_jax, integral2d_np, integral_path,
    load_integrals, merge_shard_sats, verify_integral, write_integrals,
)
from heatmap_tpu.analytics.query import (  # noqa: F401
    VALID_OPS, parse_bbox, quantile, quantile_rows, range_sum,
    range_sum_rows, top_k_hotspots, top_k_rows, validate_op,
)
