"""Numpy-only range-query evaluators over integral pyramids.

Query semantics (docs/analytics.md): ``z`` names the SOURCE GRID zoom
— the level whose cells are being aggregated, grid side ``2**z`` — and
``bbox`` is an inclusive cell rectangle ``x0,y0,x1,y1`` (x = column,
y = row) with every coordinate in ``[0, 2**z)``. Grid zoom ``z``
corresponds to tile zoom ``z - result_delta``.

Three evaluators, each with an integral fast path and an exact
row-scan fall-through (used when a store predates integral artifacts):

- :func:`range_sum` — four corner lookups, O(1), pinned equal to the
  brute-force sum over served exact tiles.
- :func:`top_k_hotspots` — best-first coarse-to-fine descent over
  grid-aligned blocks, pruning every subtree whose range sum cannot
  reach the current k-th value. Exact for non-negative grids: a
  block's sum upper-bounds every contained cell.
- :func:`quantile` — binary search on cell-count thresholds over the
  same descent (``count_above(t)`` prunes blocks whose sum is <= t),
  finished exactly by stepping to the next occupied value.

``top_k_hotspots`` and ``quantile`` reserve their descents for rects
that are huge AND sparse; the common case sorts one vectorized dense
SAT-window reconstruction instead (see :data:`DESCENT_SPARSITY`).

All evaluators assume non-negative cell values — true for every store
this pipeline publishes (retraction stores prune to net counts and
drop non-positive cells before egress).
"""

from __future__ import annotations

import heapq
import math

import numpy as np

from heatmap_tpu.analytics.integral import IntegralPair
from heatmap_tpu.tilemath.morton import morton_decode_np

__all__ = [
    "TEMPORAL_OPS", "VALID_OPS", "level_cells", "parse_bbox", "quantile",
    "quantile_rows", "range_sum",
    "range_sum_rows", "top_k_hotspots", "top_k_rows", "validate_op",
]

#: The spatial /query operations (serve/http.py 400s and CLI flags
#: validate against this single source of truth).
VALID_OPS = ("sum", "topk", "quantile")

#: Time-axis operations (heatmap_tpu.temporal.timequery): listed
#: separately because they take a ``window`` instead of a ``bbox`` and
#: tools that sweep the spatial ops (tools/bench_query.py) must not
#: pick them up implicitly.
TEMPORAL_OPS = ("topk_growth",)


def validate_op(op: str) -> str:
    """``op`` unchanged, or a one-line ValueError naming the valid set."""
    if op not in VALID_OPS and op not in TEMPORAL_OPS:
        raise ValueError(
            f"unknown query op {op!r}: valid ops are "
            f"{', '.join(VALID_OPS + TEMPORAL_OPS)}")
    return op


def parse_bbox(text: str, zoom: int):
    """``"x0,y0,x1,y1"`` -> ``(r0, c0, r1, c1)`` inclusive cell rect.

    x = column, y = row, all in ``[0, 2**zoom)`` with ``x0 <= x1`` and
    ``y0 <= y1``; one-line ValueErrors (the /query 400 bodies)."""
    parts = str(text).split(",")
    if len(parts) != 4:
        raise ValueError(
            f"bbox must be 'x0,y0,x1,y1' (inclusive cells), got {text!r}")
    try:
        x0, y0, x1, y1 = (int(p) for p in parts)
    except ValueError:
        raise ValueError(
            f"bbox must be four integers 'x0,y0,x1,y1', got {text!r}")
    n = 1 << int(zoom)
    if not (0 <= x0 <= x1 < n and 0 <= y0 <= y1 < n):
        raise ValueError(
            f"bbox {text!r} out of range for zoom {zoom}: cells span "
            f"[0, {n}) and x0<=x1, y0<=y1")
    return y0, x0, y1, x1


# -- integral fast paths ---------------------------------------------------

#: ``top_k_hotspots`` and ``quantile`` run their Python block descents
#: only when the rect is HUGE and SPARSE — ``area > DESCENT_SPARSITY *
#: nnz`` — and otherwise sort one vectorized SAT-window reconstruction
#: (``_window_grid``). Measured crossover: a quantile bisection costs
#: ~1ms per occupied cell (64 passes x ~14 Python block visits each),
#: the dense window ~15ns per rect cell, so the descent only wins past
#: ~2**16 cells of area per occupied cell (e.g. a near-empty zoom-12
#: full-grid rect).
DESCENT_SPARSITY = 1 << 16


def _top_k_cells(rows, cols, vals, k: int):
    """Exact top-k over cell arrays with the (value desc, row asc,
    col asc) tie-break. ``np.partition`` first prunes to the tie
    closure of the k-th value so the lexsort only sees candidates —
    O(n + m log m) for m survivors instead of O(n log n)."""
    k = int(k)
    n = len(vals)
    if n > k:
        thresh = np.partition(vals, n - k)[n - k]
        keep = vals >= thresh
        rows, cols, vals = rows[keep], cols[keep], vals[keep]
    order = np.lexsort((cols, rows, -vals))[:k]
    return [(int(rows[i]), int(cols[i]), float(vals[i])) for i in order]


def range_sum(pair: IntegralPair, rect) -> float:
    """Exact rect sum in O(1): four SAT corner lookups."""
    return pair.range_sum(*rect)


def top_k_hotspots(pair: IntegralPair, rect, k: int, *,
                   sparsity: int = DESCENT_SPARSITY):
    """Top-``k`` hottest cells in the rect as ``(row, col, value)``.

    Best-first descent: a max-heap of grid-aligned blocks keyed by
    ``(-range_sum, r0, c0)``. A popped single cell outranks everything
    still queued (non-negative cells: a block's sum >= any contained
    cell), so cells emerge in exact descending order with the
    (value desc, row asc, col asc) tie-break — matching the exhaustive
    ``np.lexsort((cols, rows, -values))`` oracle. Zero-sum blocks are
    never queued, so only occupied cells are returned.

    The descent is output-sensitive on peaked grids but degenerates on
    FLAT ones (near-equal block sums defeat the pruning), so unless
    the rect is huge and sparse (``area > sparsity * nnz``, see
    :data:`DESCENT_SPARSITY`) a dense SAT-window reconstruction is
    sorted instead — same cells, same order."""
    r0, c0, r1, c1 = rect
    nnz = pair.cell_count(r0, c0, r1, c1)
    area = (r1 - r0 + 1) * (c1 - c0 + 1)
    if nnz and area <= sparsity * nnz:
        sub = _window_grid(pair, rect)
        rr, cc = np.nonzero(sub > 0.0)
        return _top_k_cells(rr.astype(np.int64) + r0,
                            cc.astype(np.int64) + c0, sub[rr, cc], k)
    out: list = []
    total = pair.range_sum(r0, c0, r1, c1)
    heap = [(-total, r0, c0, r1, c1)] if total > 0.0 else []
    while heap and len(out) < int(k):
        negs, br0, bc0, br1, bc1 = heapq.heappop(heap)
        if br0 == br1 and bc0 == bc1:
            out.append((br0, bc0, -negs))
            continue
        rm = (br0 + br1) // 2
        cm = (bc0 + bc1) // 2
        for qr0, qr1 in ((br0, rm), (rm + 1, br1)):
            if qr0 > qr1:
                continue
            for qc0, qc1 in ((bc0, cm), (cm + 1, bc1)):
                if qc0 > qc1:
                    continue
                s = pair.range_sum(qr0, qc0, qr1, qc1)
                if s > 0.0:
                    heapq.heappush(heap, (-s, qr0, qc0, qr1, qc1))
    return out


def _count_above(pair: IntegralPair, rect, t: float) -> int:
    """Cells in the rect with value strictly above ``t`` (``t >= 0``).

    Pruned descent: non-negative cells mean a block whose range sum is
    <= t cannot hold a cell above t, so whole subtrees drop out."""
    stack = [rect]
    count = 0
    while stack:
        br0, bc0, br1, bc1 = stack.pop()
        s = pair.range_sum(br0, bc0, br1, bc1)
        if s <= t:
            continue
        if br0 == br1 and bc0 == bc1:
            count += 1
            continue
        rm = (br0 + br1) // 2
        cm = (bc0 + bc1) // 2
        for qr0, qr1 in ((br0, rm), (rm + 1, br1)):
            if qr0 > qr1:
                continue
            for qc0, qc1 in ((bc0, cm), (cm + 1, bc1)):
                if qc0 > qc1:
                    continue
                stack.append((qr0, qc0, qr1, qc1))
    return count


def _min_above(pair: IntegralPair, rect, t: float):
    """Smallest cell value strictly above ``t`` in the rect, or None."""
    best = None
    stack = [rect]
    while stack:
        br0, bc0, br1, bc1 = stack.pop()
        s = pair.range_sum(br0, bc0, br1, bc1)
        if s <= t:
            continue
        if br0 == br1 and bc0 == bc1:
            if best is None or s < best:
                best = s
            continue
        rm = (br0 + br1) // 2
        cm = (bc0 + bc1) // 2
        for qr0, qr1 in ((br0, rm), (rm + 1, br1)):
            if qr0 > qr1:
                continue
            for qc0, qc1 in ((bc0, cm), (cm + 1, bc1)):
                if qc0 > qc1:
                    continue
                stack.append((qr0, qc0, qr1, qc1))
    return best


def _window_grid(pair: IntegralPair, rect) -> np.ndarray:
    """The rect's dense cell grid, recovered from the SAT: slice the
    window, double-difference it (exact in f64 for integer grids, the
    :func:`~heatmap_tpu.analytics.grid_from_sat` identity). One
    vectorized O(area) pass — the fast path when the rect holds many
    occupied cells and per-cell descent would dominate."""
    r0, c0, r1, c1 = rect
    sat = pair.sat
    win = np.zeros((r1 - r0 + 2, c1 - c0 + 2), np.float64)
    win[1:, 1:] = sat[r0:r1 + 1, c0:c1 + 1]
    if r0:
        win[0, 1:] = sat[r0 - 1, c0:c1 + 1]
    if c0:
        win[1:, 0] = sat[r0:r1 + 1, c0 - 1]
        if r0:
            win[0, 0] = sat[r0 - 1, c0 - 1]
    return np.diff(np.diff(win, axis=0), axis=1)


def _window_values(pair: IntegralPair, rect) -> np.ndarray:
    """Occupied cell values of the rect's dense window."""
    sub = _window_grid(pair, rect)
    return sub[sub > 0.0]


def quantile(pair: IntegralPair, rect, q: float, *,
             sparsity: int = DESCENT_SPARSITY):
    """q-quantile over the rect's OCCUPIED cells, or None when empty.

    Defined as the ``ceil(q * nnz)``-th smallest occupied value
    (1-based; q=0 -> min, q=1 -> max) — equivalently the smallest
    occupied value with at most ``nnz - ceil(q*nnz)`` cells strictly
    above it. The common path sorts one vectorized SAT-window
    reconstruction of the rect. When the rect is huge and sparse
    (``area > sparsity * nnz``, see :data:`DESCENT_SPARSITY`) the
    O(area) window would dwarf the occupied set, so it instead runs a
    binary search on value thresholds driven by the pruned
    ``count_above`` descent, finished EXACTLY by stepping ``lo`` to
    the next occupied value until the count condition holds. Both
    paths equal the sorted-values oracle."""
    q = float(q)
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile q must be in [0, 1], got {q!r}")
    r0, c0, r1, c1 = rect
    nnz = pair.cell_count(r0, c0, r1, c1)
    if nnz == 0:
        return None
    idx = max(0, math.ceil(q * nnz) - 1)  # 0-based order statistic
    area = (r1 - r0 + 1) * (c1 - c0 + 1)
    if area <= sparsity * nnz:
        return float(np.sort(_window_values(pair, rect))[idx])
    allowed = nnz - 1 - idx               # cells allowed strictly above
    # Invariants: count_above(lo) > allowed, count_above(hi) <= allowed
    # (every occupied value is positive and <= the rect's total sum).
    lo = 0.0
    hi = pair.range_sum(r0, c0, r1, c1)
    for _ in range(64):
        mid = 0.5 * (lo + hi)
        if _count_above(pair, rect, mid) <= allowed:
            hi = mid
        else:
            lo = mid
    while True:
        s = _min_above(pair, rect, lo)  # exists: count_above(lo) > 0
        if _count_above(pair, rect, s) <= allowed:
            return float(s)
        lo = s


# -- exact row-scan fall-throughs ------------------------------------------


def level_cells(level, rect):
    """(rows, cols, values) of the level's stored cells inside the
    rect, positives only — stored levels never carry non-positive
    cells (delta stores prune them at merge), and the integral paths
    above never emit them, so both paths agree on "occupied"."""
    r0, c0, r1, c1 = rect
    rows, cols = morton_decode_np(level.codes)
    rows = rows.astype(np.int64)
    cols = cols.astype(np.int64)
    m = ((rows >= r0) & (rows <= r1) & (cols >= c0) & (cols <= c1)
         & (level.values > 0.0))
    return rows[m], cols[m], level.values[m]


def range_sum_rows(level, rect) -> float:
    """Fall-through rect sum from the exact level rows — O(rows)."""
    _, _, vals = level_cells(level, rect)
    return float(vals.sum()) if len(vals) else 0.0


def top_k_rows(level, rect, k: int):
    """Fall-through top-k over the rect's cells with the same
    (value desc, row asc, col asc) tie-break."""
    rows, cols, vals = level_cells(level, rect)
    return _top_k_cells(rows, cols, vals, k)


def quantile_rows(level, rect, q: float):
    """Fall-through quantile: sort the rect's occupied values."""
    q = float(q)
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile q must be in [0, 1], got {q!r}")
    _, _, vals = level_cells(level, rect)
    if not len(vals):
        return None
    vals = np.sort(vals)
    return float(vals[max(0, math.ceil(q * len(vals)) - 1)])
