"""Analytics metric handles on the shared obs registry.

Module-level, created once at import (the delta/metrics.py pattern):
handles survive ``registry.reset()`` between tests and self-gate on
``registry.enabled``. Semantics are documented in
docs/observability.md.
"""

from __future__ import annotations

from heatmap_tpu import obs

_registry = obs.get_registry()

QUERY_SECONDS = _registry.histogram(
    "query_seconds",
    "Wall-clock of answering one /query request, per operation",
    labelnames=("op",),
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0))
INTEGRAL_BYTES = _registry.gauge(
    "integral_bytes_total",
    "Bytes of the most recently published integral artifact, per "
    "pyramid level",
    labelnames=("level",))
