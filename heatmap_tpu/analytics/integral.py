"""Integral (summed-area) pyramids: O(1) range aggregates per level.

``write_integrals`` turns every ``level_z*.npz`` below ``max_z`` in a
level directory into an ``integral-z{zoom:02d}.npz`` sitting alongside
it: per (user, timespan) pair, the 2D inclusive prefix sum
(summed-area table, the integral-histogram construction of arxiv
1711.01919) of the dense per-cell count grid, plus the matching
occupancy SAT (prefix counts of ``grid != 0``). Any axis-aligned
rectangle sum or occupied-cell count is then four corner lookups::

    sum(r0..r1, c0..c1) = S[r1,c1] - S[r0-1,c1] - S[r1,c0-1]
                          + S[r0-1,c0-1]

with the ``r0 == 0`` / ``c0 == 0`` terms dropped.

Exactness contract (docs/analytics.md): the SAT is exact in binary f64
for integer-valued grids — partial sums of integers stay below 2**53
and round-trip bit-exact, and the recovery of the grid by finite
differences (:func:`grid_from_sat`) is exact for the same reason — so
``/query?op=sum`` is pinned EQUAL to the brute-force sum over served
exact tiles, not approximately so. Float-weighted grids get the usual
f64 rounding instead of the pin.

Morton-shard composition: the prefix scan is linear, so the SAT of a
merged pyramid equals the elementwise sum of per-shard SATs
(:func:`merge_shard_sats`). A Morton-range shard scans only its own
cells; every cell it does NOT hold is a zero, so the cross-shard
contribution reduces to the constant boundary offsets the elementwise
sum applies in one pass — the same fix-up shape as the PR 13
first-holder exchange, with every boundary term already inside a
shard's own scan.

Artifact schema ``heatmap-tpu.integral.v1`` (compressed npz): scalars
``zoom``/``coarse_zoom``/``n`` (grid side ``2**zoom``), per-pair
``users``/``timespans``, and stacked ``sat`` (f64, ``(pairs, n, n)``)
/ ``cnt`` (int64 occupancy SAT, same shape) slabs. Writes are atomic
(tmp + os.replace) under the ``sink.write`` retry site, the same
publish discipline as the exact level files — a torn integral can only
be a crash artifact, which the delta recovery sweep quarantines
(delta/recover.py, reason ``torn_integral``).

Numpy-only at module level: jax loads lazily inside the ``*_jax``
functions (tests/test_obs.py greps), because this module sits on the
serve tier's read path.
"""

from __future__ import annotations

import os
import zipfile

import numpy as np

from heatmap_tpu import faults, obs
from heatmap_tpu.synopsis.transform import grid_from_rows_np

__all__ = [
    "DEFAULT_MAX_Z", "HARD_MAX_Z", "SCHEMA", "IntegralPair", "build_pair",
    "grid_from_sat", "integral2d_jax", "integral2d_np", "integral_path",
    "load_integrals", "merge_shard_sats", "verify_integral",
    "write_integrals",
]

SCHEMA = "heatmap-tpu.integral.v1"

#: Levels with zoom < DEFAULT_MAX_Z get an integral; finer levels stay
#: row-only (their grids are big and range queries over leaf detail
#: fall through to the exact rows — slower but still correct).
DEFAULT_MAX_Z = 10

#: Refusal ceiling: a 2**HARD_MAX_Z square f64 SAT is 128 MiB per
#: (user, timespan) pair — beyond this the dense scan is the wrong
#: tool and the caller gets a loud error, not an OOM. Matches the
#: synopsis subsystem's ceiling (synopsis/build.py).
HARD_MAX_Z = 12


def integral2d_np(grid: np.ndarray) -> np.ndarray:
    """2D inclusive prefix sum (summed-area table) of a 2D grid, f64."""
    grid = np.asarray(grid, np.float64)
    if grid.ndim != 2:
        raise ValueError(f"integral2d wants a 2D grid, got {grid.shape}")
    return np.cumsum(np.cumsum(grid, axis=0), axis=1)


_JIT_SCAN = None


def integral2d_jax(grid):
    """jit'd twin of :func:`integral2d_np` for the cascade path.

    jit specializes on the (padded) grid shape, so pad-bucketed callers
    (pipeline.bucketing) compile once per bucket — the same
    bucketed-compile contract as ``grid_from_rows_jax``. No Pallas
    kernel is warranted: two cumsums are O(n^2) adds with trivial
    arithmetic intensity; XLA's scan lowering is already memory-bound.
    """
    global _JIT_SCAN
    if _JIT_SCAN is None:
        import jax
        import jax.numpy as jnp

        def _scan(g):
            return jnp.cumsum(jnp.cumsum(g, axis=0), axis=1)

        _JIT_SCAN = jax.jit(_scan)
    return _JIT_SCAN(grid)


def merge_shard_sats(parts) -> np.ndarray:
    """SAT of a Morton-sharded level from per-shard SATs.

    The prefix scan is linear: ``SAT(sum of shard grids) ==
    sum(SAT(shard grid))``, exactly, because each shard's grid is zero
    outside its Z-order range. The elementwise sum IS the
    boundary-offset fix-up — a shard's scan already carries the
    constant offset its cells contribute to every rectangle that
    crosses its range boundary, mirroring how the PR 13 rollup ships
    only boundary tiles at merge."""
    parts = [np.asarray(p, np.float64) for p in parts]
    if not parts:
        raise ValueError("merge_shard_sats needs at least one shard SAT")
    out = parts[0].copy()
    for p in parts[1:]:
        if p.shape != out.shape:
            raise ValueError(
                f"shard SAT shapes differ: {p.shape} != {out.shape}")
        out += p
    return out


def grid_from_sat(sat: np.ndarray) -> np.ndarray:
    """Inverse of :func:`integral2d_np` by finite differences — exact
    in f64 for integer-valued grids (differences of exact integers)."""
    sat = np.asarray(sat, np.float64)
    return np.diff(np.diff(sat, axis=0, prepend=0.0), axis=1, prepend=0.0)


class IntegralPair:
    """One (user, timespan) slice of one level's integral pyramid."""

    __slots__ = ("user", "timespan", "zoom", "n", "sat", "cnt")

    def __init__(self, user, timespan, zoom, sat, cnt):
        self.user = str(user)
        self.timespan = str(timespan)
        self.zoom = int(zoom)
        self.sat = np.asarray(sat, np.float64)
        self.cnt = np.asarray(cnt, np.float64)
        self.n = int(self.sat.shape[0])

    @staticmethod
    def _rect(table, r0, c0, r1, c1) -> float:
        s = table[r1, c1]
        if r0:
            s -= table[r0 - 1, c1]
        if c0:
            s -= table[r1, c0 - 1]
        if r0 and c0:
            s += table[r0 - 1, c0 - 1]
        return float(s)

    def range_sum(self, r0, c0, r1, c1) -> float:
        """Sum over the inclusive cell rect — four corner lookups."""
        return self._rect(self.sat, r0, c0, r1, c1)

    def cell_count(self, r0, c0, r1, c1) -> int:
        """Occupied (nonzero) cells in the inclusive rect, O(1)."""
        return int(round(self._rect(self.cnt, r0, c0, r1, c1)))

    def grid(self) -> np.ndarray:
        """Dense ``(n, n)`` count grid recovered from the SAT."""
        return grid_from_sat(self.sat)

    def with_extras(self, rows, cols, values) -> "IntegralPair":
        """New pair with delta rows folded in: recover the grid,
        scatter-add the extras, rescan. Exact for integer grids, so a
        base integral plus live delta rows answers queries identically
        to a full recompute over base ⊕ deltas."""
        grid = self.grid()
        np.add.at(grid, (np.asarray(rows, np.int64),
                         np.asarray(cols, np.int64)),
                  np.asarray(values, np.float64))
        return IntegralPair(self.user, self.timespan, self.zoom,
                            integral2d_np(grid),
                            integral2d_np((grid != 0.0).astype(np.float64)))


def build_pair(rows, cols, values, zoom: int):
    """Integral of one pair's level rows -> ``(sat, cnt)`` SATs."""
    if zoom > HARD_MAX_Z:
        raise ValueError(
            f"integral grids stop at zoom {HARD_MAX_Z} "
            f"(2^{HARD_MAX_Z} side); got zoom {zoom}")
    n = 1 << int(zoom)
    grid = grid_from_rows_np(rows, cols, values, n)
    return (integral2d_np(grid),
            np.cumsum(np.cumsum((grid != 0.0).astype(np.int64), axis=0),
                      axis=1))


def integral_path(level_dir: str, zoom: int) -> str:
    return os.path.join(level_dir, f"integral-z{int(zoom):02d}.npz")


def _pair_strings(cols):
    """user/timespan string columns from a loaded OR finalized level
    dict (same dual shape as synopsis/build.py)."""
    if "user" in cols:
        return np.asarray(cols["user"], str), np.asarray(
            cols["timespan"], str)
    return (np.asarray(cols["user_names"], str)[cols["user_idx"]],
            np.asarray(cols["timespan_names"], str)[cols["timespan_idx"]])


def write_integrals(level_dir: str, levels=None, *,
                    max_z: int = DEFAULT_MAX_Z) -> dict:
    """Build + atomically publish integral artifacts for every level
    below ``max_z`` in ``level_dir``.

    ``levels`` (``{zoom: cols}``) skips re-reading the level files when
    the caller already holds them (the egress sink and compaction do).
    Returns ``{zoom: {"pairs": n, "bytes": n}}`` and emits one
    ``integral_built`` event per level.
    """
    from heatmap_tpu.analytics import metrics
    from heatmap_tpu.io.sinks import LevelArraysSink

    if levels is None:
        levels = LevelArraysSink.load(level_dir)
    out: dict = {}
    for zoom in sorted(levels):
        if int(zoom) >= max_z:
            continue
        cols = levels[zoom]
        users, tss = _pair_strings(cols)
        rows = np.asarray(cols["row"], np.int64)
        cls = np.asarray(cols["col"], np.int64)
        vals = np.asarray(cols["value"], np.float64)
        pair_key = np.char.add(np.char.add(users, "|"), tss)
        p_users, p_tss = [], []
        sat_parts, cnt_parts = [], []
        for pk in np.unique(pair_key):
            sel = pair_key == pk
            user, _, ts = str(pk).partition("|")
            sat, cnt = build_pair(rows[sel], cls[sel], vals[sel],
                                  int(zoom))
            p_users.append(user)
            p_tss.append(ts)
            sat_parts.append(sat)
            cnt_parts.append(cnt)
        n = 1 << int(zoom)
        final = integral_path(level_dir, int(zoom))
        payload = {
            "schema": np.asarray(SCHEMA),
            "zoom": np.asarray(int(zoom)),
            "coarse_zoom": np.asarray(int(cols["coarse_zoom"])),
            "n": np.asarray(n),
            "users": np.asarray(p_users, str),
            "timespans": np.asarray(p_tss, str),
            "sat": (np.stack(sat_parts) if sat_parts
                    else np.zeros((0, n, n), np.float64)),
            "cnt": (np.stack(cnt_parts).astype(np.int64) if cnt_parts
                    else np.zeros((0, n, n), np.int64)),
        }
        tmp = final + ".tmp"

        def _publish():
            with open(tmp, "wb") as f:
                np.savez_compressed(f, **payload)
            os.replace(tmp, final)

        faults.retry_call(_publish, site="sink.write", key="integral")
        nbytes = os.path.getsize(final)
        out[int(zoom)] = {"pairs": len(p_users), "bytes": nbytes}
        if obs.metrics_enabled():
            metrics.INTEGRAL_BYTES.set(nbytes, level=str(int(zoom)))
        obs.emit("integral_built", zoom=int(zoom), pairs=len(p_users),
                 bytes=nbytes, path=final)
    return out


def verify_integral(path: str) -> str | None:
    """None when ``path`` is a readable v1 integral artifact, else a
    fault description (the recovery sweep's quarantine detail)."""
    try:
        with np.load(path) as z:
            if str(z["schema"]) != SCHEMA:
                return f"schema {z['schema']!r} != {SCHEMA!r}"
            n = int(z["n"])
            pairs = len(z["users"])
            if len(z["timespans"]) != pairs:
                return "users/timespans length mismatch"
            if z["sat"].shape != (pairs, n, n):
                return (f"sat shape {z['sat'].shape} != "
                        f"{(pairs, n, n)}")
            if z["cnt"].shape != (pairs, n, n):
                return (f"cnt shape {z['cnt'].shape} != "
                        f"{(pairs, n, n)}")
    except (OSError, ValueError, KeyError, zipfile.BadZipFile) as e:
        return repr(e)
    return None


def load_integrals(level_dir: str) -> dict:
    """``{zoom: [IntegralPair, ...]}`` for every readable integral
    artifact in ``level_dir``. Unreadable or wrong-schema files are
    SKIPPED, not raised — serving falls through to exact rows and the
    recovery sweep owns quarantining torn artifacts."""
    out: dict = {}
    try:
        names = sorted(os.listdir(level_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("integral-z") and name.endswith(".npz")):
            continue
        full = os.path.join(level_dir, name)
        try:
            with np.load(full) as z:
                if str(z["schema"]) != SCHEMA:
                    continue
                zoom = int(z["zoom"])
                users = z["users"]
                tss = z["timespans"]
                sat = z["sat"]
                cnt = z["cnt"]
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            continue
        pairs = []
        for i in range(len(users)):
            pairs.append(IntegralPair(users[i], tss[i], zoom,
                                      sat[i], cnt[i]))
        out[zoom] = pairs
    return out
