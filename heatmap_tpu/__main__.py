"""``python -m heatmap_tpu`` — CLI entry (see heatmap_tpu.cli)."""

import sys

from heatmap_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
