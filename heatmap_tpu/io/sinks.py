"""Egress sinks: heatmap blob writers.

TPU-native replacement for the reference's Cassandra egress
(``write_heatmap_dataframes``, reference heatmap.py:149-150): records
are ``(id, heatmap)`` pairs where ``id`` is the composite
``user|timespan|coarseTileId`` key and ``heatmap`` is the JSON dict of
detail-tile counts (reference heatmap.py:156-157). The reference's
Cassandra ``append`` mode upserts by primary key (SURVEY.md §8.12);
every sink here has the same last-write-wins-per-id semantics.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterable

import numpy as np

from heatmap_tpu import faults, obs
from heatmap_tpu.io.png import raster_to_png


class BlobSink:
    """Base: consumes (id, heatmap-dict-or-json) records.

    ``write`` runs each ``write_one`` under the unified ``sink.write``
    retry policy (faults/retry.py): the fault check fires *before* the
    write starts and every concrete ``write_one`` is an upsert by id,
    so retried writes are idempotent."""

    #: Metric label for sink_blobs_written_total{sink=...}.
    KIND = "blob"

    def write(self, records: Iterable[tuple]) -> int:
        n = 0
        for blob_id, heatmap in records:
            faults.retry_call(self.write_one, blob_id, heatmap,
                              site="sink.write", key=self.KIND)
            n += 1
        if n and obs.metrics_enabled():
            obs.SINK_BLOBS.inc(n, sink=self.KIND)
        return n

    def write_one(self, blob_id: str, heatmap) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _as_json(heatmap) -> str:
    return heatmap if isinstance(heatmap, str) else json.dumps(heatmap)


class SinkConfigError(RuntimeError, faults.NonRetryable):
    """Deterministic sink misconfiguration — never retried."""


class MemorySink(BlobSink):
    """Dict-backed sink (tests, small jobs). Upsert-by-id."""

    KIND = "memory"

    def __init__(self):
        self.blobs: dict[str, str] = {}

    def write_one(self, blob_id, heatmap):
        self.blobs[blob_id] = _as_json(heatmap)


@dataclasses.dataclass
class JSONLBlobSink(BlobSink):
    """One ``{"id": ..., "heatmap": ...}`` JSON object per line.

    Append-oriented like the reference's write mode; ``load`` applies
    last-write-wins per id, reproducing Cassandra upsert semantics
    (reference heatmap.py:150, SURVEY.md §8.12)."""

    path: str
    _f: object = dataclasses.field(default=None, repr=False)

    KIND = "jsonl"

    def _open(self):
        if self._f is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._f = open(self.path, "a")
        return self._f

    @staticmethod
    def _line(blob_id, heatmap) -> str:
        return json.dumps({"id": blob_id, "heatmap": _as_json(heatmap)})

    def write_one(self, blob_id, heatmap):
        self._open().write(self._line(blob_id, heatmap) + "\n")

    def write(self, records) -> int:
        """Bulk write: writelines in chunks (one buffered flush per ~16k
        blobs instead of a Python write call per blob — the default CLI
        sink sees millions of records from big jobs). writelines avoids
        the doubled peak memory a joined string would cost when blob
        bodies are large."""
        f = self._open()
        n = 0
        nbytes = 0
        counting = obs.metrics_enabled()
        lines = []
        for blob_id, heatmap in records:
            lines.append(self._line(blob_id, heatmap) + "\n")
            if len(lines) >= 16384:
                faults.retry_call(f.writelines, lines,
                                  site="sink.write", key=self.KIND)
                n += len(lines)
                if counting:
                    nbytes += sum(len(ln) for ln in lines)
                lines.clear()
        if lines:
            faults.retry_call(f.writelines, lines,
                              site="sink.write", key=self.KIND)
            n += len(lines)
            if counting:
                nbytes += sum(len(ln) for ln in lines)
        if n and counting:
            obs.SINK_BLOBS.inc(n, sink=self.KIND)
            obs.SINK_BYTES.inc(nbytes, sink=self.KIND)
        return n

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    @staticmethod
    def load(path) -> dict[str, dict]:
        out: dict[str, dict] = {}
        with open(path) as f:
            for line in f:
                if line.strip():
                    rec = json.loads(line)
                    out[rec["id"]] = json.loads(rec["heatmap"])
        return out


@dataclasses.dataclass
class DirectoryBlobSink(BlobSink):
    """One file per blob id (id sanitized into a filename); overwrite =
    native upsert."""

    root: str

    KIND = "dir"

    def write_one(self, blob_id, heatmap):
        os.makedirs(self.root, exist_ok=True)
        fname = blob_id.replace(os.sep, "_") + ".json"
        with open(os.path.join(self.root, fname), "w") as f:
            f.write(_as_json(heatmap))


@dataclasses.dataclass
class CassandraBlobSink(BlobSink):
    """Cassandra egress to ``rhom.heatmaps`` (reference
    heatmap.py:149-150; schema ``(id text PRIMARY KEY, heatmap text)``,
    reference heatmap.py:157). Needs an injected ``session`` (the
    cassandra-driver package is not baked into this image); batches
    async inserts ``concurrency`` deep."""

    session: object = None
    keyspace: str = "rhom"  # reference heatmap.py:150
    table: str = "heatmaps"  # reference heatmap.py:150
    KIND = "cassandra"
    concurrency: int = 128
    _pending: list = dataclasses.field(default_factory=list, repr=False)

    def write_one(self, blob_id, heatmap):
        if self.session is None:
            raise SinkConfigError(
                "CassandraBlobSink needs a cassandra-driver session "
                "(not baked into this image); use JSONL/Directory sinks "
                "or inject session=..."
            )
        cql = (
            f"INSERT INTO {self.keyspace}.{self.table} (id, heatmap) "
            "VALUES (%s, %s)"
        )
        self._pending.append(
            self.session.execute_async(cql, (blob_id, _as_json(heatmap)))
        )
        if len(self._pending) >= self.concurrency:
            self._drain()

    def _drain(self):
        for fut in self._pending:
            fut.result()
        self._pending.clear()

    def close(self):
        if self._pending:
            self._drain()


@dataclasses.dataclass
class LevelArraysSink:
    """Columnar egress: one compressed ``.npz`` per pyramid level.

    Consumes the finalized level arrays
    (pipeline.cascade.emit_level_arrays output) directly — the same
    information as the reference blob format (blob id =
    user|timespan|coarse tile + detail-tile counts, reference
    heatmap.py:54-55,79-90) but as columns, with no per-blob Python
    dict assembly anywhere. This is the bulk-egress surface: a
    Cassandra/warehouse loader can stream the columns straight into
    batched writes. Jobs route here automatically when the sink has
    ``write_levels`` (pipeline.batch._finish_blobs).

    Files are ``level_z{zoom}.npz`` (or ``.parquet`` with
    ``format="parquet"`` — pyarrow, one row group, ready for warehouse
    bulk loads) holding row/col/value, user/timespan (unicode),
    coarse_row/coarse_col and zoom/coarse_zoom; rewrites are atomic
    (tmp + rename), so reruns upsert whole levels — the columnar
    analog of upsert-by-id.
    """

    path: str
    format: str = "npz"
    #: Also publish wavelet ``synopsis-z*.npz`` artifacts alongside the
    #: exact levels (``arrays-synopsis:DIR`` spec; heatmap_tpu.synopsis).
    synopses: bool = False
    #: Also publish ``integral-z*.npz`` summed-area artifacts alongside
    #: the exact levels (``arrays-integral:DIR`` spec;
    #: heatmap_tpu.analytics).
    integrals: bool = False
    #: Also publish zero-copy ``tilefs-z*.bin`` mirrors alongside the
    #: exact levels (``arrays-tilefs:DIR`` spec; heatmap_tpu.tilefs) —
    #: the serving tier mmaps these instead of decompressing npz.
    tilefs: bool = False

    def __post_init__(self):
        if self.format not in ("npz", "npz-compressed", "parquet"):
            raise ValueError(
                f"format must be 'npz', 'npz-compressed' or 'parquet', "
                f"got {self.format!r}"
            )
        os.makedirs(self.path, exist_ok=True)

    #: Per-row columns; user/timespan are stored dictionary-encoded
    #: (``user_idx``/``timespan_idx`` int32 + the small ``user_names``/
    #: ``timespan_names`` tables in npz; native DictionaryArray columns
    #: named ``user``/``timespan`` in parquet). ``load`` materializes
    #: plain ``user``/``timespan`` string columns either way.
    COLUMNS = ("row", "col", "value", "user_idx", "timespan_idx",
               "coarse_row", "coarse_col")

    def write_levels(self, levels) -> int:
        rows = 0
        if self.synopses or self.integrals or self.tilefs:
            levels = list(levels)  # consumed twice: levels + derived
        for lvl in levels:
            out = {k: np.asarray(lvl[k]) for k in self.COLUMNS}
            out["zoom"] = np.asarray(lvl["zoom"])
            out["coarse_zoom"] = np.asarray(lvl["coarse_zoom"])
            ext = "npz" if self.format.startswith("npz") else self.format
            final = os.path.join(
                self.path, f"level_z{lvl['zoom']:02d}.{ext}"
            )
            tmp = final + ".tmp"

            def _publish_level():
                # One retried unit per level: stage to tmp, then atomic
                # replace — re-running after a transient failure (or an
                # injected sink.write fault) rewrites the whole level.
                if self.format == "parquet":
                    import pyarrow as pa
                    import pyarrow.parquet as pq

                    n = len(out["value"])
                    cols = {}
                    for k, v in out.items():
                        if k == "user_idx":
                            cols["user"] = pa.DictionaryArray.from_arrays(
                                pa.array(v), pa.array(lvl["user_names"])
                            )
                        elif k == "timespan_idx":
                            cols["timespan"] = pa.DictionaryArray.from_arrays(
                                pa.array(v), pa.array(lvl["timespan_names"])
                            )
                        else:
                            cols[k] = np.full(n, v) if v.ndim == 0 else v
                    pq.write_table(pa.table(cols), tmp)
                else:
                    out["user_names"] = np.asarray(lvl["user_names"])
                    out["timespan_names"] = np.asarray(lvl["timespan_names"])
                    # Plain savez by default: zlib cost dominated egress
                    # (~17s of a 40s 2M-point job); columns are already
                    # compact (int32 + dictionary encoding).
                    save = (np.savez_compressed
                            if self.format == "npz-compressed" else np.savez)
                    with open(tmp, "wb") as f:
                        save(f, **out)
                os.replace(tmp, final)

            faults.retry_call(_publish_level, site="sink.write", key="arrays")
            rows += len(out["value"])
            if obs.metrics_enabled():
                obs.SINK_ROWS.inc(len(out["value"]), sink="arrays")
                obs.SINK_BYTES.inc(os.path.getsize(final), sink="arrays")
        if self.synopses:
            # Build from the in-memory finalized levels — no re-read.
            # Synopsis artifacts are npz regardless of the level format.
            from heatmap_tpu.synopsis import write_synopses

            write_synopses(self.path,
                           {int(lvl["zoom"]): lvl for lvl in levels})
        if self.integrals:
            from heatmap_tpu.analytics import write_integrals

            write_integrals(self.path,
                            {int(lvl["zoom"]): lvl for lvl in levels})
        if self.tilefs:
            # Zero-copy mirrors from the same in-memory levels. The
            # writer re-materializes the dictionary-encoded columns —
            # tilefs pairs are split on the string keys, exactly like
            # TileStore._build_from_levels.
            from heatmap_tpu.tilefs import format as tilefs_format

            tilefs_format.write_tilefs_from_loaded(self.path, {
                int(lvl["zoom"]): {
                    "row": lvl["row"], "col": lvl["col"],
                    "value": lvl["value"],
                    "coarse_zoom": lvl["coarse_zoom"],
                    "user": np.asarray(lvl["user_names"])[
                        np.asarray(lvl["user_idx"])],
                    "timespan": np.asarray(lvl["timespan_names"])[
                        np.asarray(lvl["timespan_idx"])],
                } for lvl in levels})
        return rows

    def write(self, records):
        raise TypeError(
            "LevelArraysSink is columnar-only (write_levels); use a "
            "blob sink (jsonl:/dir:/memory:) for per-blob records"
        )

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @staticmethod
    def load(path: str) -> dict:
        """{zoom: dict-of-columns} for every level file in ``path``.

        ``user``/``timespan`` come back as materialized string columns
        regardless of the on-disk dictionary encoding, so consumers are
        format-agnostic.
        """
        out = {}
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            if not name.startswith("level_z"):
                continue
            if name.endswith(".npz"):
                with np.load(full) as z:
                    cols = {k: z[k] for k in z.files}
                for col, names in (("user", "user_names"),
                                   ("timespan", "timespan_names")):
                    if names in cols:
                        cols[col] = cols[names][cols.pop(f"{col}_idx")]
                        del cols[names]
                    # else: pre-dictionary-encoding file, plain columns
                out[int(cols["zoom"])] = cols
            elif name.endswith(".parquet"):
                import pyarrow as pa
                import pyarrow.parquet as pq

                t = pq.read_table(full)
                cols = {}
                for k in t.column_names:
                    c = t[k].combine_chunks()
                    if pa.types.is_dictionary(c.type):
                        c = c.dictionary_decode()
                        cols[k] = np.asarray(c).astype(str)
                    elif pa.types.is_string(c.type):
                        # pre-dictionary-encoding file
                        cols[k] = np.asarray(c).astype(str)
                    else:
                        cols[k] = np.asarray(c)
                # Normalize the per-row zoom columns back to scalars so
                # both formats load identically.
                for k in ("zoom", "coarse_zoom"):
                    cols[k] = np.asarray(cols[k][0]) if len(cols[k]) else cols[k]
                out[int(cols["zoom"])] = cols
        return out


@dataclasses.dataclass
class PNGTileSink:
    """Slippy-map PNG tile tree: ``root/z/x/y.png``.

    Renders dense window rasters (ops.histogram.Window layout: rows are
    tile rows, cols are tile columns at ``window.zoom``) into standard
    z/x/y web-map tiles of ``tile_px`` pixels, one pixel per detail
    cell ``pixel_delta`` zooms finer. With the default
    ``pixel_delta=8``, a z10 tile's 256x256 pixels are the z18 detail
    counts — the dense-raster analog of the reference's 32x32 blob
    fan-in (DETAIL_ZOOM_DELTA=5, reference heatmap.py:16,89)."""

    root: str
    pixel_delta: int = 8
    log_scale: bool = True

    def write_window(self, raster, window, vmax=None) -> int:
        """Write all complete z/x/y tiles covered by ``raster`` (a
        (window.height, window.width) counts array at window.zoom).
        Tile zoom is ``window.zoom - pixel_delta``. Returns #tiles."""
        raster = np.asarray(raster)
        px = 1 << self.pixel_delta
        tz = window.zoom - self.pixel_delta
        if tz < 0:
            raise ValueError(
                f"window zoom {window.zoom} < pixel_delta {self.pixel_delta}"
            )
        if window.row0 % px or window.col0 % px:
            raise ValueError("window origin must align to tile size")
        n_ty, n_tx = raster.shape[0] // px, raster.shape[1] // px
        vmax = vmax if vmax is not None else float(raster.max() or 1)
        count = 0
        for ty in range(n_ty):
            for tx in range(n_tx):
                block = raster[ty * px : (ty + 1) * px, tx * px : (tx + 1) * px]
                if not block.any():
                    continue
                y = window.row0 // px + ty
                x = window.col0 // px + tx
                d = os.path.join(self.root, str(tz), str(x))
                os.makedirs(d, exist_ok=True)
                with open(os.path.join(d, f"{y}.png"), "wb") as f:
                    f.write(
                        raster_to_png(block, log_scale=self.log_scale, vmax=vmax)
                    )
                count += 1
        return count


def per_process_sink_spec(spec: str, process_index: int) -> str:
    """Derive this process's sink spec for sharded multi-host egress.

    Every host writes its own shard (parallel.multihost
    ``egress="sharded"``), so path-backed sinks need distinct per-host
    paths on shared storage: file sinks get a ``.pNNN`` suffix,
    directory sinks a ``hostNNN/`` subdirectory. ``memory:`` is
    process-local already and ``cassandra:`` upserts by blob id, so
    concurrent per-host writers need no derivation — the reference's
    reducers wrote the same table concurrently (heatmap.py:149-150).
    """
    kind, _, rest = spec.partition(":")
    tag = f"p{process_index:03d}"
    if kind == "jsonl" or (not rest and spec.endswith((".jsonl", ".ndjson"))):
        path = rest or spec
        return f"jsonl:{path}.{tag}"
    if kind in ("arrays", "arrays-parquet", "arrays-synopsis",
                "arrays-integral", "arrays-tilefs", "dir"):
        return f"{kind}:{os.path.join(rest, 'host' + f'{process_index:03d}')}"
    if kind in ("memory", "cassandra"):
        return spec
    raise ValueError(f"unrecognized sink spec {spec!r}")


#: Sink spec kinds ``open_sink`` accepts, in help order.
SINK_KINDS = ("jsonl", "arrays", "arrays-parquet", "arrays-synopsis",
              "arrays-integral", "arrays-tilefs", "dir", "memory",
              "cassandra")


def validate_sink_spec(spec: str) -> str:
    """Reject an unknown sink kind with a one-line error naming the
    valid ones. Meant for argument-parse time: a typo like ``josnl:x``
    must fail before backend init and ingest, not after the job has
    already run for minutes. Returns ``spec`` so it can wrap an
    argparse ``type=``."""
    kind, sep, _ = spec.partition(":")
    if (sep and kind in SINK_KINDS) or spec.endswith((".jsonl", ".ndjson")):
        return spec
    raise ValueError(
        f"unrecognized sink spec {spec!r}: kind must be one of "
        f"{', '.join(SINK_KINDS)} (e.g. jsonl:blobs.jsonl), or a bare "
        f".jsonl/.ndjson path"
    )


def open_sink(spec: str) -> BlobSink:
    """CLI sink spec: ``jsonl:PATH``, ``dir:PATH``, ``memory:``,
    ``cassandra:``, ``arrays:DIR`` (columnar per-level npz) or a bare
    ``.jsonl`` path."""
    validate_sink_spec(spec)
    kind, _, rest = spec.partition(":")
    if kind == "jsonl":
        return JSONLBlobSink(rest)
    if kind == "arrays":
        return LevelArraysSink(rest)
    if kind == "arrays-parquet":
        return LevelArraysSink(rest, format="parquet")
    if kind == "arrays-synopsis":
        return LevelArraysSink(rest, synopses=True)
    if kind == "arrays-integral":
        return LevelArraysSink(rest, integrals=True)
    if kind == "arrays-tilefs":
        return LevelArraysSink(rest, tilefs=True)
    if kind == "dir":
        return DirectoryBlobSink(rest)
    if kind == "memory":
        return MemorySink()
    if kind == "cassandra":
        return CassandraBlobSink()
    return JSONLBlobSink(spec)
