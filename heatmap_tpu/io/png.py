"""Zero-dependency PNG tile rendering for heatmap rasters.

The reference stores JSON count dicts only; PNG tile emission is part
of the new framework's egress surface (BASELINE.md config 3 /
BASELINE.json north star: "PNG/JSON tile emission"). The encoder is
pure stdlib (zlib + struct) so egress has no imaging dependency; the
colormap is applied vectorized on the host.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

# A compact perceptual heat colormap (black->purple->orange->white),
# piecewise-linear control points in RGB.
_STOPS = np.array(
    [
        [0, 0, 0],
        [60, 0, 90],
        [140, 20, 60],
        [220, 90, 20],
        [255, 180, 40],
        [255, 255, 220],
    ],
    np.float64,
)


def _chunk(tag: bytes, payload: bytes) -> bytes:
    return (
        struct.pack(">I", len(payload))
        + tag
        + payload
        + struct.pack(">I", zlib.crc32(tag + payload) & 0xFFFFFFFF)
    )


def png_bytes(img: np.ndarray) -> bytes:
    """Encode an image to PNG. ``img``: (H, W) u8 grayscale, (H, W, 3)
    RGB, or (H, W, 4) RGBA, dtype uint8."""
    img = np.ascontiguousarray(img)
    if img.dtype != np.uint8:
        raise ValueError("png_bytes wants uint8")
    if img.ndim == 2:
        color_type = 0
    elif img.ndim == 3 and img.shape[2] == 3:
        color_type = 2
    elif img.ndim == 3 and img.shape[2] == 4:
        color_type = 6
    else:
        raise ValueError(f"unsupported image shape {img.shape}")
    h, w = img.shape[:2]
    ihdr = struct.pack(">IIBBBBB", w, h, 8, color_type, 0, 0, 0)
    # Filter byte 0 (None) prepended to each scanline.
    flat = img.reshape(h, -1)
    raw = np.empty((h, flat.shape[1] + 1), np.uint8)
    raw[:, 0] = 0
    raw[:, 1:] = flat
    return b"".join(
        [
            b"\x89PNG\r\n\x1a\n",
            _chunk(b"IHDR", ihdr),
            _chunk(b"IDAT", zlib.compress(raw.tobytes(), 6)),
            _chunk(b"IEND", b""),
        ]
    )


def colorize(raster: np.ndarray, *, log_scale: bool = True,
             vmax: float | None = None, alpha: bool = True) -> np.ndarray:
    """Counts -> RGBA heat image (uint8). Zero-count cells are fully
    transparent when ``alpha``; intensity is log1p-scaled by default
    (heatmap counts are heavy-tailed)."""
    v = np.asarray(raster, np.float64)
    x = np.log1p(v) if log_scale else v
    top = float(np.log1p(vmax)) if (vmax is not None and log_scale) else (
        float(vmax) if vmax is not None else float(x.max()) or 1.0
    )
    t = np.clip(x / (top or 1.0), 0.0, 1.0)
    pos = t * (len(_STOPS) - 1)
    i0 = np.clip(pos.astype(np.int64), 0, len(_STOPS) - 2)
    frac = (pos - i0)[..., None]
    rgb = _STOPS[i0] * (1 - frac) + _STOPS[i0 + 1] * frac
    out = np.empty((*v.shape, 4), np.uint8)
    out[..., :3] = np.clip(rgb, 0, 255).astype(np.uint8)
    out[..., 3] = np.where(v > 0, 255, 0) if alpha else 255
    return out


def raster_to_png(raster, **kw) -> bytes:
    """Counts raster -> PNG bytes (RGBA heat tile)."""
    return png_bytes(colorize(np.asarray(raster), **kw))
