"""HMPB: the framework's binary columnar point format (mmap ingest).

CSV decoding tops out at parser speed (native/pointcodec.cpp, ~150
MB/s/core); production-scale reruns of the same dataset shouldn't pay
it twice. HMPB stores points in the pipeline's *fast layout* — numeric
columns plus pre-routed integer group ids (reference heatmap.py:64-70
routing applied once, at conversion) — so ingest is a memory map and
per-batch slicing runs at memory bandwidth. The reference's analog is
the Cassandra SSTable scan behind the connector (reference
heatmap.py:137), which it re-decodes every run.

Layout (explicitly little-endian, including on big-endian hosts):

    magic   b"HMPB\\x01\\n"
    u64     header_len (JSON bytes, excluding its pad)
    bytes   header JSON: {"n": N, "names": [routed group names],
                          "columns": [...]}  (id order; names[i] is
                          routed id i, -1 = excluded x-user),
            NUL-padded so the data section starts 8-byte aligned
    f64[N]  latitude
    f64[N]  longitude
    f64[N]  value (OPTIONAL: per-point weight, weighted jobs)
    i64[N]  timestamp (TS_MISSING sentinel = INT64_MIN)
    i32[N]  routed group id
    u8[N]   background flag (reference heatmap.py:28-29)

Sections are contiguous, in the order above (widest first, u8 last);
the header's ``columns`` list names exactly the sections present, in
file order, so readers compute offsets from the header (files without
the optional value column list five columns and older readers of such
files see the original layout unchanged). Every column is *naturally*
aligned for its element type — the data section starts 8-byte aligned,
f64/i64 sections keep that, and the narrower sections follow
widest-first — so external readers can mmap and cast each column
pointer directly.

Timestamp units: values pass through from the source unchanged
(the reference's location feed carried epoch-milliseconds, reference
heatmap.py:26); datetime/date objects are normalized to epoch-ms.
The column is self-consistent per file, but HMPB does not convert
between source unit conventions — mixing epoch-second and epoch-ms
sources and then using dated timespans is on the operator.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

MAGIC = b"HMPB\x01\n"
# Canonical missing-timestamp sentinel (INT64_MIN); re-exported here
# because it is part of the on-disk format contract.
from heatmap_tpu.pipeline.timespan import TS_MISSING  # noqa: E402

_COLUMNS = (
    ("latitude", "<f8"),
    ("longitude", "<f8"),
    ("timestamp", "<i8"),
    ("routed", "<i4"),
    ("background", "u1"),
)

#: Every column an HMPB header may name, with its storage dtype. The
#: file's actual layout is the header's ``columns`` list in order.
_COLUMN_DTYPES = dict(_COLUMNS) | {"value": "<f8"}


def write_hmpb(path: str, latitude, longitude, routed, names,
               timestamp=None, background=None, value=None):
    """Write one HMPB file from fast-layout columns (atomic rename).

    ``value`` (optional f64 per-point weights) adds the value section —
    readers expose it and weighted fast jobs consume it; files without
    it keep the original five-column layout byte-for-byte."""
    lat = np.ascontiguousarray(latitude, "<f8")
    lon = np.ascontiguousarray(longitude, "<f8")
    n = lat.shape[0]
    rid = np.ascontiguousarray(routed, "<i4")
    ts = (
        np.full(n, TS_MISSING, "<i8")
        if timestamp is None
        else np.ascontiguousarray(timestamp, "<i8")
    )
    bg = (
        np.zeros(n, "u1")
        if background is None
        else np.ascontiguousarray(background, "u1")
    )
    val = None if value is None else np.ascontiguousarray(value, "<f8")
    sections = [("latitude", lat), ("longitude", lon)]
    if val is not None:
        sections.append(("value", val))
    sections += [("timestamp", ts), ("routed", rid), ("background", bg)]
    for name, arr in sections[1:]:
        if arr.shape[0] != n:
            raise ValueError(f"{name} has {arr.shape[0]} rows, expected {n}")
    if rid.size and int(rid.max(initial=-1)) >= len(names):
        raise ValueError("routed ids exceed the names table")
    header = json.dumps({
        "n": int(n),
        "names": list(names),
        "columns": [c for c, _ in sections],
    }).encode()
    # NUL-pad so the data section (magic + u64 + header + pad) starts
    # 8-byte aligned: every later section is then aligned too (columns
    # are ordered widest-first).
    pad = (-(len(MAGIC) + 8 + len(header))) % 8
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(np.uint64(len(header)).astype("<u8").tobytes())
        f.write(header)
        f.write(b"\x00" * pad)
        for _, arr in sections:
            arr.tofile(f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


class HMPBSource:
    """Memory-mapped HMPB reader yielding fast-layout batches.

    ``fast_batches`` is the pipeline.run_job_fast input contract
    (latitude/longitude/timestamp/background/routed arrays +
    new_group_names); ``batches`` adapts to the string-column Source
    contract for the generic (slower) pipeline paths.
    """

    #: Resident host bytes/point under FAST ingest: the mmap'd columns
    #: are page-cache (reclaimable), and only the per-batch routed
    #: views materialize (~28 B: f64 coords + i32 group + i64 stamp).
    #: Consulted by pipeline._auto_points_in_flight(fast=True) so a
    #: big HMPB file that fits single-shot is not demoted to the
    #: chunked path by the 160 B string-ingest constant (ADVICE r3).
    fast_host_bytes_per_point = 30

    def __init__(self, path: str):
        self.path = path
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if f.read(len(MAGIC)) != MAGIC:
                raise ValueError(f"{path}: not an HMPB file")
            (hlen,) = np.frombuffer(f.read(8), "<u8")
            if int(hlen) > size:
                raise ValueError(
                    f"{path}: corrupt header length {int(hlen)} "
                    f"(file is {size} bytes)"
                )
            try:
                header = json.loads(f.read(int(hlen)).decode())
                self.n = int(header["n"])
                self.names = list(header["names"])
                columns = list(header.get("columns")
                               or [c for c, _ in _COLUMNS])
            except (UnicodeDecodeError, ValueError, KeyError, TypeError) as e:
                # json.JSONDecodeError is a ValueError; surface every
                # header-corruption shape as one clean error.
                raise ValueError(f"{path}: corrupt HMPB header: {e}") from e
            if self.n < 0:
                raise ValueError(f"{path}: corrupt HMPB header: n={self.n}")
            unknown = [c for c in columns if c not in _COLUMN_DTYPES]
            if unknown:
                raise ValueError(
                    f"{path}: HMPB header names unknown column(s) "
                    f"{unknown} (written by a newer format revision?)"
                )
            required = [c for c, _ in _COLUMNS]
            missing = [c for c in required if c not in columns]
            if missing or len(set(columns)) != len(columns):
                raise ValueError(
                    f"{path}: corrupt HMPB header: columns={columns} "
                    f"(missing {missing or 'none'}, duplicates "
                    f"{'present' if len(set(columns)) != len(columns) else 'none'})"
                )
            self._data_off = f.tell() + (-f.tell()) % 8  # header NUL pad
        self.has_value = "value" in columns
        offsets = {}
        off = self._data_off
        for name in columns:
            dtype = _COLUMN_DTYPES[name]
            offsets[name] = (off, dtype)
            off += self.n * np.dtype(dtype).itemsize
        expected = off
        if size < expected:
            raise ValueError(
                f"{path}: truncated ({size} bytes, need {expected})"
            )
        # Map the file once; per-batch reads are plain slices of these
        # column views (no per-batch open/mmap syscalls).
        self._mm = np.memmap(path, dtype="u1", mode="r")
        self._cols = {
            name: self._mm[o : o + self.n * np.dtype(dt).itemsize].view(dt)
            for name, (o, dt) in offsets.items()
        }
        self._maps = offsets  # column offset table (alignment contract)

    def _col(self, name, lo, hi):
        return self._cols[name][lo:hi]

    def close(self) -> None:
        """Release the file map. The source yields no batches after
        this. Drops the source's own references only — the map is
        unmapped by refcount immediately when no batch views escaped
        (the cli probe case), or as soon as outstanding zero-copy batch
        views die. Never force-closes the mmap: numpy memmap views
        don't pin the buffer against ``mmap.close()``, so forcing it
        would turn a held view into a segfault."""
        self._cols = {}
        self._mm = None
        self.n = 0  # closed source iterates as empty, not KeyError

    def fast_batches(self, batch_size: int = 1 << 20):
        sent_names = False
        for lo in range(0, self.n, batch_size):
            hi = min(lo + batch_size, self.n)
            out = {
                "latitude": np.asarray(self._col("latitude", lo, hi)),
                "longitude": np.asarray(self._col("longitude", lo, hi)),
                "timestamp": np.asarray(self._col("timestamp", lo, hi)),
                "routed": np.asarray(self._col("routed", lo, hi)),
                "background": np.asarray(
                    self._col("background", lo, hi)
                ).astype(bool),
                "new_group_names": [] if sent_names else list(self.names),
            }
            if self.has_value:
                out["value"] = np.asarray(self._col("value", lo, hi))
            yield out
            sent_names = True

    def batches(self, batch_size: int = 1 << 20):
        """String-column Source view (for the generic pipeline paths).

        user_id strings are reconstructed from the routed-name table —
        excluded x-users come back as the canonical ``"x"`` (the
        original id wasn't stored; routing is identical since only the
        prefix matters, reference heatmap.py:65) and route-pooled ids
        as ``"rt-"``-less ``"route"``... which would re-route to its own
        group, so they come back as ``"rt-0"`` to preserve routing.
        """
        for b in self.fast_batches(batch_size):
            rid = b["routed"]
            users = []
            for r in rid:
                if r < 0:
                    users.append("x")
                else:
                    name = self.names[r]
                    users.append("rt-0" if name == "route" else name)
            ts = b["timestamp"]
            out = {
                "latitude": b["latitude"],
                "longitude": b["longitude"],
                "user_id": users,
                "source": [
                    "background" if bg else "gps" for bg in b["background"]
                ],
                "timestamp": [
                    None if t == TS_MISSING else int(t) for t in ts
                ],
            }
            if "value" in b:
                out["value"] = b["value"]
            yield out


@dataclasses.dataclass
class HMPBDirSource:
    """A directory of ``*.hmpb`` shard files as one source.

    The multi-file analog of Cassandra token ranges for binary point
    data: each file is a deterministic range unit, so the source is
    range-shardable (``shard_index``/``shard_count`` interleave files
    across hosts — parallel.multihost.shard_source re-instantiates with
    the process assignment) and a failed shard re-reads exactly via
    ``range_batches(i)``. Per-file name tables are remapped into one
    global intern as files stream, so ``fast_batches`` keeps the
    run_job_fast contract (routed ids index the cumulative
    ``new_group_names`` stream; ids stay stable across files).
    """

    #: See HMPBSource.fast_host_bytes_per_point (files stream one at a
    #: time, so the per-point residency matches the single-file case).
    fast_host_bytes_per_point = 30

    path: str
    shard_index: int = 0
    shard_count: int = 1

    def __post_init__(self):
        if self.shard_count < 1 or not (
            0 <= self.shard_index < self.shard_count
        ):
            raise ValueError(
                f"invalid shard assignment: shard_index={self.shard_index} "
                f"shard_count={self.shard_count} (need 0 <= index < count)"
            )
        self.files = sorted(
            os.path.join(self.path, f)
            for f in os.listdir(self.path)
            if f.endswith(".hmpb")
        )
        if not self.files:
            raise ValueError(f"no .hmpb files under {self.path!r}")

    @property
    def n_ranges(self) -> int:
        return len(self.files)

    def my_files(self):
        """This shard's interleaved (global_index, path) assignment."""
        return [
            (i, f) for i, f in enumerate(self.files)
            if i % self.shard_count == self.shard_index
        ]

    def fast_batches(self, batch_size: int = 1 << 20):
        intern: dict = {}
        names: list = []
        emitted = 0
        for _, path in self.my_files():
            src = HMPBSource(path)
            # file-local id -> global id (global intern grows in
            # first-seen order, matching the reader contract).
            local_to_global = np.empty(max(len(src.names), 1), np.int32)
            for li, name in enumerate(src.names):
                gi = intern.get(name)
                if gi is None:
                    gi = len(names)
                    intern[name] = gi
                    names.append(name)
                local_to_global[li] = gi
            # convert_to_hmpb writes every part with the SAME names
            # table, so the remap is usually the identity — skip the
            # per-batch copies then (mmap ingest stays copy-free).
            identity = (
                len(src.names) <= len(names)
                and bool(
                    (local_to_global[: len(src.names)]
                     == np.arange(len(src.names))).all()
                )
            )
            try:
                for b in src.fast_batches(batch_size):
                    routed = np.asarray(b["routed"], np.int32)
                    if not identity:
                        routed = np.where(
                            routed >= 0,
                            local_to_global[np.maximum(routed, 0)], -1,
                        ).astype(np.int32)
                    out = {
                        "latitude": b["latitude"],
                        "longitude": b["longitude"],
                        "timestamp": b["timestamp"],
                        "routed": routed,
                        "background": b["background"],
                        "new_group_names": names[emitted:],
                    }
                    if "value" in b:
                        out["value"] = b["value"]
                    yield out
                    emitted = len(names)
            finally:
                # Unmap each shard as soon as its batches are consumed
                # instead of accumulating every file's map until GC
                # (close tolerates consumers still holding batch views).
                src.close()

    def close(self) -> None:
        """No held maps: per-file sources open and close per iteration."""

    def range_batches(self, index: int, batch_size: int = 1 << 20):
        """String-column batches of ONE file (deterministic
        re-execution of a failed shard, global file index)."""
        return HMPBSource(self.files[index]).batches(batch_size)

    def batches(self, batch_size: int = 1 << 20):
        """String-column Source view over this shard's files."""
        for _, path in self.my_files():
            yield from HMPBSource(path).batches(batch_size)


def _stamp_to_i64(s) -> int:
    """Timestamp -> stored i64: ints/strings pass through as epoch
    values; datetime/date become epoch-ms (the shape timespan._to_date
    consumes — reference heatmap.py:26 carried epoch-ms)."""
    import datetime as _dt

    if s in (None, ""):
        return TS_MISSING
    if isinstance(s, _dt.datetime):
        if s.tzinfo is None:
            s = s.replace(tzinfo=_dt.timezone.utc)
        return int(s.timestamp() * 1000)
    if isinstance(s, _dt.date):
        return int(_dt.datetime(
            s.year, s.month, s.day, tzinfo=_dt.timezone.utc
        ).timestamp() * 1000)
    return int(float(s))


def convert_to_hmpb(source_spec: str, out_path: str,
                    batch_size: int = 1 << 20,
                    shard_rows: int | None = None) -> dict:
    """Convert any source spec to HMPB (columns held in memory once).

    CSV inputs use the native decoder's fast path end-to-end; other
    sources route user ids host-side. With ``shard_rows``, ``out_path``
    becomes a DIRECTORY of ``part-NNNNN.hmpb`` files of at most that
    many rows each (the HMPBDirSource range-shard layout for multihost
    ingest); every part carries the full shared names table, so parts
    are independently readable and ids are consistent without
    remapping. Returns {"n": ..., "groups": ...}.
    """
    if shard_rows is not None and shard_rows < 1:
        raise ValueError(f"shard_rows must be >= 1, got {shard_rows}")
    lats, lons, tss, rids, bgs, vals = [], [], [], [], [], []
    names: list = []

    def ingest_fast(batches):
        for b in batches:
            names.extend(b["new_group_names"])
            lats.append(np.asarray(b["latitude"], np.float64))
            lons.append(np.asarray(b["longitude"], np.float64))
            tss.append(np.asarray(b["timestamp"], np.int64))
            rids.append(np.asarray(b["routed"], np.int32))
            bgs.append(np.asarray(b["background"], np.uint8))
            if "value" in b:
                vals.append(np.asarray(b["value"], np.float64))

    kind, _, rest = source_spec.partition(":")
    is_csv = kind == "csv" or (not rest and source_spec.endswith(".csv"))
    is_hmpb = kind == "hmpb" or (not rest and source_spec.endswith(".hmpb"))
    native_ok = False
    if is_csv:
        try:
            from heatmap_tpu.native import parse_csv_batches

            native_ok = True
        except ImportError:
            pass
        if native_ok:
            # The native decoder knows the reference columns only; a
            # weighted CSV must take the string path so its value
            # column lands in the HMPB file.
            from heatmap_tpu.io.sources import CSVSource

            if CSVSource(rest or source_spec).has_value_column():
                native_ok = False
    if native_ok:
        ingest_fast(parse_csv_batches(
            rest if kind == "csv" else source_spec, batch_size, fast=True,
        ))
    elif is_hmpb:
        # Already in the fast layout: columnar copy, no per-row work.
        ingest_fast(HMPBSource(rest or source_spec).fast_batches(batch_size))
    else:
        from heatmap_tpu.io.sources import open_source
        from heatmap_tpu.pipeline.groups import route_user

        src = open_source(source_spec)
        intern: dict = {}
        for b in src.batches(batch_size):
            m = len(b["latitude"])
            rid = np.empty(m, np.int32)
            for i, uid in enumerate(b["user_id"]):
                name = route_user(uid)
                if name is None:
                    rid[i] = -1
                    continue
                g = intern.get(name)
                if g is None:
                    g = len(names)
                    intern[name] = g
                    names.append(name)
                rid[i] = g
            src_col = b.get("source") or []
            bg = np.asarray(
                [s == "background" for s in src_col] if len(src_col) else
                np.zeros(m, bool)
            ).astype(np.uint8)
            stamps = b.get("timestamp")
            if stamps is None or len(stamps) == 0:
                ts = np.full(m, TS_MISSING, np.int64)
            else:
                ts = np.asarray([_stamp_to_i64(s) for s in stamps], np.int64)
            lats.append(np.asarray(b["latitude"], np.float64))
            lons.append(np.asarray(b["longitude"], np.float64))
            tss.append(ts)
            rids.append(rid)
            bgs.append(bg)
            if "value" in b:
                vals.append(np.asarray(b["value"], np.float64))

    n = sum(len(a) for a in lats)
    if vals and sum(len(a) for a in vals) != n:
        # All-or-nothing: a partial value column would silently mean
        # "weight 1.0" for whole slices of the dataset.
        raise ValueError(
            f"{source_spec}: value column present on only part of the "
            "source batches; cannot write a consistent HMPB value "
            "section"
        )
    lat = np.concatenate(lats) if n else np.empty(0)
    lon = np.concatenate(lons) if n else np.empty(0)
    rid = np.concatenate(rids) if n else np.empty(0, np.int32)
    ts = np.concatenate(tss) if n else None
    bg = np.concatenate(bgs) if n else None
    val = np.concatenate(vals) if (n and vals) else None
    if shard_rows is None:
        write_hmpb(out_path, lat, lon, rid, names,
                   timestamp=ts, background=bg, value=val)
        return {"n": n, "groups": len(names), "output": out_path}
    os.makedirs(out_path, exist_ok=True)
    n_parts = max(1, -(-n // shard_rows))
    # A re-convert with fewer parts must not leave stale shards behind:
    # HMPBDirSource reads every *.hmpb in the directory as data.
    for f in os.listdir(out_path):
        if f.endswith(".hmpb"):
            os.remove(os.path.join(out_path, f))
    for p in range(n_parts):
        lo, hi = p * shard_rows, min((p + 1) * shard_rows, max(n, 0))
        write_hmpb(
            os.path.join(out_path, f"part-{p:05d}.hmpb"),
            lat[lo:hi], lon[lo:hi], rid[lo:hi], names,
            timestamp=None if ts is None else ts[lo:hi],
            background=None if bg is None else bg[lo:hi],
            value=None if val is None else val[lo:hi],
        )
    return {"n": n, "groups": len(names), "output": out_path,
            "parts": n_parts}
