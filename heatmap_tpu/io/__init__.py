"""heatmap_tpu.io — host-side ingest sources and egress sinks.

Replaces the reference's Spark-connector storage boundary
(``get_rows`` / ``write_heatmap_dataframes``, reference
heatmap.py:131-150) with columnar batch readers and upsert-by-id blob
writers; PNG tile rendering is new surface (BASELINE.md config 3).
"""

from heatmap_tpu.io.sources import (  # noqa: F401
    COLUMNS,
    CassandraConfig,
    CassandraSource,
    CosmosDBSource,
    CSVSource,
    JSONLSource,
    ParquetSource,
    Source,
    SyntheticSource,
    open_source,
)
from heatmap_tpu.io.hmpb import (  # noqa: F401
    HMPBDirSource,
    HMPBSource,
    convert_to_hmpb,
    write_hmpb,
)
from heatmap_tpu.io.sinks import (  # noqa: F401
    BlobSink,
    CassandraBlobSink,
    DirectoryBlobSink,
    JSONLBlobSink,
    LevelArraysSink,
    MemorySink,
    PNGTileSink,
    open_sink,
    validate_sink_spec,
)
from heatmap_tpu.io.png import colorize, png_bytes, raster_to_png  # noqa: F401
from heatmap_tpu.io.merge import (  # noqa: F401
    merge_blob_files,
    merge_level_dirs,
)
