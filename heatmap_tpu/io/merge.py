"""Merging egress artifacts: blob dicts and columnar level shards.

The reference gets merging for free from Cassandra upserts (every run
appends into ``rhom.heatmaps``, reference heatmap.py:149-150); this
framework's sharded egress instead writes per-host FILES
(``jsonl:...p000``, per-host ``arrays:`` dirs — parallel/multihost.py
scatter_blobs/scatter_levels), so an operator needs an explicit merge
to get one artifact. Colliding blob ids SUM their inner dicts (the
linearity every aggregation path relies on), and non-summable
collisions raise instead of resolving last-write-wins.

This module is the device-free CORE: parallel/multihost.py imports the
merge semantics from here (its collectives then move the same data
across hosts), and the CLI ``merge`` subcommand runs here directly.
Nothing in this module touches a device or initializes a jax backend
(the package root does import the jax library, but no ``jax.devices()``
/ jit runs here), so merging shards works offline — including against
a dead accelerator relay, whose backend init would otherwise hang
(tests/test_io.py pins the no-backend-init property).
"""

from __future__ import annotations

import json

import numpy as np

from heatmap_tpu.io.sinks import JSONLBlobSink, LevelArraysSink

#: Per-row columns of a finalized level (the write_levels schema).
_LEVEL_ROW_COLS = LevelArraysSink.COLUMNS


def _merge_blob_values(a, b):
    """Sum two blob values that may be JSON strings of {tile: count}.

    Collisions MUST be summable {tile: number} dicts — that is the
    only shape this framework's egress emits, so anything else at a
    merge point is corruption and raises (the loud-overflow
    convention; round-2 review flagged the old silent
    last-process-wins resolution).
    """
    decode = isinstance(a, str)
    da = json.loads(a) if decode else a
    db = json.loads(b) if isinstance(b, str) else b
    if not (isinstance(da, dict) and isinstance(db, dict)):
        raise ValueError(
            f"colliding blob values are not mergeable dicts "
            f"({type(da).__name__} vs {type(db).__name__})"
        )
    out = dict(da)
    for k, v in db.items():
        if k not in out:  # no collision: shape constraints don't apply
            out[k] = v
            continue
        prev = out[k]
        if not (isinstance(v, (int, float))
                and isinstance(prev, (int, float))):
            raise ValueError(
                f"non-numeric blob collision for detail tile {k!r} "
                f"({type(prev).__name__} + {type(v).__name__})"
            )
        out[k] = prev + v
    return json.dumps(out) if decode else out


def merge_blob_parts(parts) -> dict:
    """Fold per-host blob sub-dicts into one dict, summing collisions
    (the same linearity as gather_blobs, applied to one owner shard)."""
    merged: dict = {}
    for part in parts:
        for key, val in part.items():
            merged[key] = (
                _merge_blob_values(merged[key], val) if key in merged else val
            )
    return merged


def merge_level_parts(parts) -> list:
    """Merge per-source finalized-level subsets into merged levels.

    Re-maps each part's dictionary-encoded user/timespan indices into
    merged (sorted, deduplicated) name tables, concatenates rows, and
    re-aggregates collisions — rows of a blob that straddled host
    ingest shards — by summing ``value`` (counts and weighted sums are
    both linear). Output rows are sorted by (timespan, user, row, col)
    for run-to-run determinism.
    """
    by_zoom: dict[int, list[dict]] = {}
    for part in parts:
        for lvl in part:
            by_zoom.setdefault(int(lvl["zoom"]), []).append(lvl)
    merged_levels = []
    for zoom in sorted(by_zoom, reverse=True):
        subs = by_zoom[zoom]
        user_names = np.unique(np.concatenate(
            [np.asarray(s["user_names"]) for s in subs]
        )) if subs else np.asarray([], dtype="U1")
        ts_names = np.unique(np.concatenate(
            [np.asarray(s["timespan_names"]) for s in subs]
        )) if subs else np.asarray([], dtype="U1")
        cols = {}
        for key in _LEVEL_ROW_COLS:
            if key == "user_idx":
                cols[key] = np.concatenate([
                    np.searchsorted(
                        user_names, np.asarray(s["user_names"])
                    )[np.asarray(s["user_idx"])].astype(np.int32)
                    if len(s["user_idx"]) else
                    np.asarray([], np.int32)
                    for s in subs
                ])
            elif key == "timespan_idx":
                cols[key] = np.concatenate([
                    np.searchsorted(
                        ts_names, np.asarray(s["timespan_names"])
                    )[np.asarray(s["timespan_idx"])].astype(np.int32)
                    if len(s["timespan_idx"]) else
                    np.asarray([], np.int32)
                    for s in subs
                ])
            else:
                cols[key] = np.concatenate(
                    [np.asarray(s[key]) for s in subs]
                )
        order = np.lexsort(
            (cols["col"], cols["row"], cols["user_idx"], cols["timespan_idx"])
        )
        for key in _LEVEL_ROW_COLS:
            cols[key] = cols[key][order]
        n = len(cols["row"])
        if n:
            same = np.zeros(n, bool)
            same[1:] = (
                (cols["timespan_idx"][1:] == cols["timespan_idx"][:-1])
                & (cols["user_idx"][1:] == cols["user_idx"][:-1])
                & (cols["row"][1:] == cols["row"][:-1])
                & (cols["col"][1:] == cols["col"][:-1])
            )
            starts = np.flatnonzero(~same)
            sums = np.add.reduceat(cols["value"], starts)
            for key in _LEVEL_ROW_COLS:
                cols[key] = cols[key][starts]
            cols["value"] = sums
        lvl = dict(cols)
        lvl["zoom"] = zoom
        lvl["coarse_zoom"] = int(subs[0]["coarse_zoom"])
        lvl["user_names"] = user_names
        lvl["timespan_names"] = ts_names
        merged_levels.append(lvl)
    return merged_levels


def merge_blob_files(paths) -> dict:
    """Merge JSONL blob files -> {blob_id: decoded dict}.

    Disjoint ids union; colliding ids sum per detail tile (a blob
    whose detail tiles straddled host shards, or the same job run
    twice — sums are what Cassandra upsert-with-reaggregation would
    have produced). Non-numeric collisions raise.
    """
    return merge_blob_parts(JSONLBlobSink.load(p) for p in paths)


def _loaded_to_finalized(cols) -> dict:
    """A LevelArraysSink.load level (materialized string user/timespan
    columns) -> the finalized write_levels format (dictionary-encoded
    indices + name tables) merge_level_parts consumes."""
    user_names, u_idx = np.unique(
        np.asarray(cols["user"], str), return_inverse=True
    )
    ts_names, t_idx = np.unique(
        np.asarray(cols["timespan"], str), return_inverse=True
    )
    return {
        "zoom": int(cols["zoom"]),
        "coarse_zoom": int(cols["coarse_zoom"]),
        "row": np.asarray(cols["row"]),
        "col": np.asarray(cols["col"]),
        "value": np.asarray(cols["value"]),
        "user_idx": u_idx.astype(np.int32),
        "timespan_idx": t_idx.astype(np.int32),
        "user_names": user_names,
        "timespan_names": ts_names,
        "coarse_row": np.asarray(cols["coarse_row"]),
        "coarse_col": np.asarray(cols["coarse_col"]),
    }


def merge_level_dirs(dirs) -> list:
    """Merge LevelArraysSink dirs -> finalized level dicts
    (write_levels input format), re-aggregated by
    (timespan, user, row, col) with values summed — the same core as
    the cross-host columnar merge (merge_level_parts).

    Zoom sets union across shards; shards disagreeing on a level's
    coarse_zoom are not shards of one job and raise.
    """
    loaded = [LevelArraysSink.load(d) for d in dirs]
    zooms = sorted(set().union(*(set(l) for l in loaded)))
    for zoom in zooms:
        coarse = {int(l[zoom]["coarse_zoom"]) for l in loaded if zoom in l}
        if len(coarse) != 1:
            raise ValueError(
                f"level z{zoom}: shards disagree on coarse_zoom "
                f"({sorted(coarse)}) — these dirs are not shards of "
                "one job"
            )
    parts = [
        [_loaded_to_finalized(levels[zoom]) for zoom in sorted(levels)]
        for levels in loaded
    ]
    return merge_level_parts(parts)
