"""Ingest sources: columnar point-batch readers.

TPU-native replacement for the reference's ``get_rows`` ingest
(reference heatmap.py:131-147): where the reference builds a Spark
DataFrame from Cassandra (keyspace ``rhom``, table ``locations``,
reference heatmap.py:137) or CosmosDB (env vars
``LOCATIONS_COSMOSDB_HOST`` / ``LOCATIONS_COSMOSDB_AUTH_KEY``,
reference heatmap.py:140-146), every source here yields **columnar
batches** — dicts of host numpy arrays / string lists — sized for
device transfer, so the hot path never sees per-row Python objects.

The reference's row contract (reference heatmap.py:25-36): columns
``latitude``, ``longitude``, ``user_id``, ``source``, ``timestamp``;
rows with ``source == "background"`` are dropped by the loader (that
filter lives in pipeline.batch, not here — sources are dumb readers).
"""

from __future__ import annotations

import csv
import dataclasses
import functools
import json
import os
from typing import Iterator

import numpy as np

from heatmap_tpu import faults, obs

#: Column names of the reference's ``rhom.locations`` table
#: (reference heatmap.py:25-36).
COLUMNS = ("latitude", "longitude", "user_id", "source", "timestamp")

#: Optional per-point weight column (BASELINE.md config 3: weighted
#: heatmap, per-point value sum). The reference table has no such
#: column — file sources pass it through when the input names one
#: literally ``value``, and batches omit the key otherwise.
VALUE_COLUMN = "value"

DEFAULT_BATCH = 1 << 20


class ConfigError(RuntimeError, faults.NonRetryable):
    """Deterministic configuration failure (missing driver/env/spec) —
    still a RuntimeError for callers, but the unified retry policy
    raises it straight through instead of burning retry attempts."""


def _empty_batch():
    return {
        "latitude": np.empty(0, np.float64),
        "longitude": np.empty(0, np.float64),
        "user_id": [],
        "source": [],
        "timestamp": [],
    }


def _finalize(cols):
    return {
        "latitude": np.asarray(cols["latitude"], np.float64),
        "longitude": np.asarray(cols["longitude"], np.float64),
        "user_id": list(cols["user_id"]),
        "source": list(cols["source"]),
        "timestamp": list(cols["timestamp"]),
    }


def _finalize_with_value(cols, vals):
    """_finalize plus the optional weight column: ``vals`` is a list of
    per-row weights (missing entries already defaulted to 1.0) or None
    when the source carries no value column."""
    out = _finalize(cols)
    if vals is not None:
        out[VALUE_COLUMN] = np.asarray(vals, np.float64)
    return out


def _count_rows(kind: str):
    """Decorator for ``batches`` impls: attribute every yielded row to
    the ``source_rows_read_total{source=<kind>}`` counter, and guard the
    stream with the unified retry policy (faults/retry.py).

    Every batch pull runs the ``source.read`` fault check; a transient
    failure (real OSError/RuntimeError or an injected fault) rebuilds
    the underlying iterator and fast-forwards past the batches already
    delivered — sound because every source iterates deterministically
    (the contract ``_scan_bounds`` and resumable jobs already rely on).
    Rows are only counted for batches actually delivered, so a replayed
    prefix never double-counts. Deterministic data errors (ValueError
    etc.) still propagate immediately from the underlying reader.
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, batch_size: int = DEFAULT_BATCH):
            from heatmap_tpu import faults

            stream = faults.resumable_iter(
                lambda: fn(self, batch_size), site="source.read", key=kind)
            for batch in stream:
                if obs.metrics_enabled():
                    obs.SOURCE_ROWS.inc(len(batch["latitude"]),
                                        source=kind)
                yield batch

        return wrapper

    return deco


class Source:
    """Base: iterable of columnar batches."""

    def batches(self, batch_size: int = DEFAULT_BATCH) -> Iterator[dict]:
        raise NotImplementedError

    def close(self) -> None:
        """Release held resources (mmaps, connections). Base: no-op —
        most sources open per-iteration; HMPBSource holds a file map."""

    def rows(self, batch_size: int = DEFAULT_BATCH) -> Iterator[dict]:
        """Row-dict view (compat with pipeline.batch.load_rows and the
        reference's per-row mappers). Slow path; prefer ``batches``."""
        for b in self.batches(batch_size):
            lat, lon = b["latitude"], b["longitude"]
            for i in range(len(lat)):
                yield {
                    "latitude": float(lat[i]),
                    "longitude": float(lon[i]),
                    "user_id": b["user_id"][i],
                    "source": b["source"][i] if b["source"] else None,
                    "timestamp": b["timestamp"][i] if b["timestamp"] else None,
                }


@dataclasses.dataclass
class SyntheticSource(Source):
    """Clustered synthetic GPS traces (hot-spot mixture over a metro
    area) with a user-id pool exercising every reference routing rule
    (plain ids, ``x``-prefixed excluded ids, ``rt-`` route ids,
    ``background`` rows; reference heatmap.py:28-29,64-70)."""

    n: int
    seed: int = 0
    n_users: int = 32
    center: tuple = (47.6, -122.3)
    spread: tuple = (0.5, 0.7)
    hotspot_frac: float = 0.25
    background_frac: float = 0.05

    #: Internal generation chunk; the point stream is a pure function of
    #: (seed, chunk index), so any ``batch_size`` yields the same points.
    CHUNK = 1 << 16

    @_count_rows("synthetic")
    def batches(self, batch_size: int = DEFAULT_BATCH) -> Iterator[dict]:
        pending = _empty_batch()
        for chunk in self._chunks():
            for k in COLUMNS:
                if isinstance(pending[k], np.ndarray):
                    pending[k] = np.concatenate([pending[k], chunk[k]])
                else:
                    pending[k] = pending[k] + chunk[k]
            while len(pending["latitude"]) >= batch_size:
                yield {k: v[:batch_size] for k, v in pending.items()}
                pending = {k: v[batch_size:] for k, v in pending.items()}
        if len(pending["latitude"]):
            yield pending

    def _chunks(self) -> Iterator[dict]:
        users = self._user_pool()
        t0 = 1_500_000_000  # fixed epoch base for reproducibility
        emitted = 0
        chunk_idx = 0
        while emitted < self.n:
            m = min(self.n - emitted, self.CHUNK)
            rng = np.random.default_rng([self.seed, chunk_idx])
            hot = rng.random(m) < self.hotspot_frac
            lat = self.center[0] + rng.normal(0, self.spread[0], m)
            lon = self.center[1] + rng.normal(0, self.spread[1], m)
            lat[hot] = self.center[0] + rng.normal(0, 0.02, int(hot.sum()))
            lon[hot] = self.center[1] + rng.normal(0, 0.03, int(hot.sum()))
            uid = rng.integers(0, len(users), m)
            bg = rng.random(m) < self.background_frac
            yield {
                "latitude": lat,
                "longitude": lon,
                "user_id": [users[i] for i in uid],
                "source": np.where(bg, "background", "gps").tolist(),
                "timestamp": (t0 + rng.integers(0, 86400 * 365, m)).tolist(),
            }
            emitted += m
            chunk_idx += 1

    def _user_pool(self):
        users = [f"user-{i}" for i in range(self.n_users)]
        users += [f"x-{i}" for i in range(max(1, self.n_users // 8))]
        users += [f"rt-{i}" for i in range(max(1, self.n_users // 8))]
        return users


@dataclasses.dataclass
class CSVSource(Source):
    """CSV reader with a header row naming (a superset of) COLUMNS.

    Numeric columns are parsed with numpy for speed; uses the native
    C++ fast parser when available (heatmap_tpu.native).

    ``read_value=None`` (auto) reads a ``value`` weight column when the
    header names one — which routes off the native decoder (it knows
    the reference's column contract only) onto the Python reader.
    Consumers that never use weights (the count-only batch job) pass
    ``read_value=False`` to keep the native fast path regardless."""

    path: str
    use_native: bool = True
    read_value: bool | None = None

    @_count_rows("csv")
    def batches(self, batch_size: int = DEFAULT_BATCH) -> Iterator[dict]:
        has_value = (self.read_value is not False
                     and self.has_value_column())
        if self.use_native and not has_value:
            try:
                from heatmap_tpu.native import parse_csv_batches
            except ImportError:
                parse_csv_batches = None
            if parse_csv_batches is not None:
                # Mid-stream errors must propagate: falling back after
                # yielding would re-read rows and double-count.
                yield from parse_csv_batches(self.path, batch_size)
                return
        with open(self.path, newline="") as f:
            reader = csv.DictReader(f)
            cols = {k: [] for k in COLUMNS}
            vals = [] if has_value else None
            for row in reader:
                cols["latitude"].append(float(row["latitude"]))
                cols["longitude"].append(float(row["longitude"]))
                cols["user_id"].append(row.get("user_id", ""))
                cols["source"].append(row.get("source", ""))
                cols["timestamp"].append(row.get("timestamp"))
                if vals is not None:
                    v = row.get(VALUE_COLUMN)
                    vals.append(float(v) if v not in (None, "") else 1.0)
                if len(cols["latitude"]) >= batch_size:
                    yield _finalize_with_value(cols, vals)
                    cols = {k: [] for k in COLUMNS}
                    vals = [] if has_value else None
            if cols["latitude"]:
                yield _finalize_with_value(cols, vals)

    def has_value_column(self) -> bool:
        """Whether the CSV header names a ``value`` weight column
        (public: convert_to_hmpb uses this to route weighted CSVs off
        the value-blind native decoder)."""
        with open(self.path, newline="") as f:
            header = next(csv.reader(f), None)
        return header is not None and VALUE_COLUMN in header


@dataclasses.dataclass
class JSONLSource(Source):
    """One JSON object per line with the reference column names.

    The FIRST data row decides whether the file is weighted
    (``read_value=None``): if it carries ``value``, every batch gets
    the column (missing entries default to 1.0); if it doesn't, a
    ``value`` appearing on a later row raises — per-batch presence
    flapping would abort weighted consumers mid-stream, and silently
    dropping late weights would corrupt sums. ``read_value=True``
    forces the weighted reading regardless of the first row (every
    missing entry is 1.0 — JSON rows are schema-less, so "column
    absent" is only ever a per-row fact); ``read_value=False`` ignores
    the column entirely."""

    path: str
    read_value: bool | None = None

    @_count_rows("jsonl")
    def batches(self, batch_size: int = DEFAULT_BATCH) -> Iterator[dict]:
        cols = {k: [] for k in COLUMNS}
        weighted = self.read_value  # None -> first data row decides
        vals = []
        line_no = 0
        with open(self.path) as f:
            for line in f:
                line_no += 1
                line = line.strip()
                if not line:
                    continue
                row = json.loads(line)
                v = row.get(VALUE_COLUMN)
                if weighted is None:  # first data row decides
                    weighted = v is not None
                elif v is not None and not weighted and self.read_value is None:
                    raise ValueError(
                        f"{self.path}:{line_no}: 'value' appears after "
                        "the first row lacked it; weighted JSONL files "
                        "must carry the column from row 1 (missing "
                        "entries default to 1.0), or pass "
                        "read_value=True to force weighted reading"
                    )
                cols["latitude"].append(float(row["latitude"]))
                cols["longitude"].append(float(row["longitude"]))
                cols["user_id"].append(row.get("user_id", ""))
                cols["source"].append(row.get("source", ""))
                cols["timestamp"].append(row.get("timestamp"))
                if weighted:
                    vals.append(float(v) if v is not None else 1.0)
                if len(cols["latitude"]) >= batch_size:
                    yield _finalize_with_value(cols, vals if weighted else None)
                    cols = {k: [] for k in COLUMNS}
                    vals = []
        if cols["latitude"]:
            yield _finalize_with_value(cols, vals if weighted else None)


@dataclasses.dataclass
class ParquetSource(Source):
    """Parquet reader (pyarrow), batched at row-group granularity.

    A ``value`` weight column in the schema passes through (nulls
    default to 1.0) unless ``read_value=False``."""

    path: str
    read_value: bool | None = None

    @_count_rows("parquet")
    def batches(self, batch_size: int = DEFAULT_BATCH) -> Iterator[dict]:
        import pyarrow.parquet as pq

        pf = pq.ParquetFile(self.path)
        for rb in pf.iter_batches(batch_size=batch_size):
            d = rb.to_pydict()
            out = {
                "latitude": np.asarray(d["latitude"], np.float64),
                "longitude": np.asarray(d["longitude"], np.float64),
                "user_id": [str(u) for u in d.get("user_id", [""] * rb.num_rows)],
                "source": [str(s) for s in d.get("source", [""] * rb.num_rows)],
                "timestamp": list(d.get("timestamp", [None] * rb.num_rows)),
            }
            if VALUE_COLUMN in d and self.read_value is not False:
                out[VALUE_COLUMN] = np.asarray(
                    [1.0 if v is None else float(v) for v in d[VALUE_COLUMN]],
                    np.float64,
                )
            yield out


@dataclasses.dataclass
class CassandraConfig:
    """The reference's hard-coded ingest endpoints as real config
    (reference heatmap.py:16-23,131-147; SURVEY.md §5 config system).

    ``endpoint`` falsy selects the CosmosDB path via env vars, exactly
    like the reference's truthiness test on
    ``LOCATION_CASSANDRA_ENDPOINT`` (reference heatmap.py:132)."""

    endpoint: str | None = "10.1.0.11"  # reference heatmap.py:23
    keyspace: str = "rhom"  # reference heatmap.py:137
    table: str = "locations"  # reference heatmap.py:137
    #: Partition-key column(s) for token() predicates. The reference's
    #: table schema is not in the repo; the Spark connector discovered
    #: it from table metadata, so it is config here.
    partition_keys: tuple = ("user_id",)
    #: Number of token ranges the Murmur3 ring is split into (the
    #: connector's input-split analog and the deterministic
    #: re-execution unit).
    n_ranges: int = 64
    cosmosdb_host_env: str = "LOCATIONS_COSMOSDB_HOST"  # heatmap.py:141
    cosmosdb_key_env: str = "LOCATIONS_COSMOSDB_AUTH_KEY"  # heatmap.py:142
    cosmosdb_database: str = "locationsdb"  # heatmap.py:144
    cosmosdb_collection: str = "locations"  # heatmap.py:145


#: Murmur3 partitioner token bounds (the Cassandra default ring).
TOKEN_MIN = -(1 << 63)
TOKEN_MAX = (1 << 63) - 1


def token_ranges(n_ranges: int) -> list:
    """Split the Murmur3 ring into ``n_ranges`` contiguous [lo, hi]
    closed intervals covering [TOKEN_MIN, TOKEN_MAX] exactly.

    Deterministic (pure arithmetic), so a failed range can be re-read
    by index on any host — the re-execution shard unit the reference
    got from the Spark connector's token-range input splits
    (reference heatmap.py:137, SURVEY.md §5 fault tolerance).
    """
    if n_ranges < 1:
        raise ValueError(f"n_ranges must be >= 1, got {n_ranges}")
    span = 1 << 64
    bounds = [TOKEN_MIN + (span * i) // n_ranges for i in range(n_ranges)]
    bounds.append(TOKEN_MAX + 1)
    return [(bounds[i], bounds[i + 1] - 1) for i in range(n_ranges)]


@dataclasses.dataclass
class CassandraSource(Source):
    """Cassandra/CosmosDB ingest (reference get_rows, heatmap.py:131-147).

    Reads the locations table as ``config.n_ranges`` deterministic
    Murmur3 token-range scans — the TPU-native analog of the Spark
    connector's token-range input splits (reference heatmap.py:137) —
    each a bounded query ``WHERE token(pk) >= lo AND token(pk) <= hi``.
    The range index is the unit of (a) multi-host sharding
    (``shard_index``/``shard_count`` interleave ranges across hosts)
    and (b) deterministic re-execution: ``range_batches(i)`` re-reads
    exactly range ``i`` after a failure (SURVEY.md §5 fault
    tolerance); partial sums are pure adds, so recovery is idempotent
    re-add of that range's points.

    The ``cassandra-driver`` package is not baked into this image —
    construction works (so config can be round-tripped), ``batches``
    raises with guidance unless a driver ``session_factory`` is
    injected. The session contract is ``session.execute(cql) ->
    iterable of rows`` (dicts or attribute objects), which real driver
    sessions satisfy; paging is the driver's job (its default
    fetch_size streams pages transparently through the iterator)."""

    config: CassandraConfig = dataclasses.field(default_factory=CassandraConfig)
    session_factory: object = None  # () -> session with .execute(cql)
    #: This host's interleaved share of the token ranges: ranges
    #: shard_index, shard_index + shard_count, ... (process-sharded
    #: ingest; parallel.multihost assigns these per process).
    shard_index: int = 0
    shard_count: int = 1

    def __post_init__(self):
        # A bad shard assignment must fail loudly: an out-of-range
        # shard_index would match no ranges and silently ingest nothing.
        if self.shard_count < 1 or not (0 <= self.shard_index < self.shard_count):
            raise ValueError(
                f"invalid shard assignment: shard_index={self.shard_index} "
                f"shard_count={self.shard_count} (need 0 <= index < count)"
            )

    def _session(self):
        cfg = self.config
        if not cfg.endpoint:
            raise ConfigError(
                "no Cassandra endpoint configured — the reference selects "
                "CosmosDB in that case (reference heatmap.py:132,140-146); "
                "use CosmosDBSource (or the cosmosdb: source spec)"
            )
        if self.session_factory is not None:
            return self.session_factory(), None
        try:
            from cassandra.cluster import Cluster
        except ImportError as e:
            raise ConfigError(
                "Cassandra ingest requires the cassandra-driver "
                "package (not baked into this image); pass "
                "session_factory=... or use CSV/JSONL/Parquet sources"
            ) from e
        cluster = Cluster([self.config.endpoint])
        return cluster.connect(), cluster

    def _range_query(self, lo: int, hi: int) -> str:
        cfg = self.config
        pk = ", ".join(cfg.partition_keys)
        return (
            f"SELECT latitude, longitude, user_id, source, timestamp "
            f"FROM {cfg.keyspace}.{cfg.table} "
            f"WHERE token({pk}) >= {lo} AND token({pk}) <= {hi}"
        )

    def my_ranges(self) -> list:
        """(index, (lo, hi)) pairs owned by this shard."""
        return [
            (i, r)
            for i, r in enumerate(token_ranges(self.config.n_ranges))
            if i % self.shard_count == self.shard_index
        ]

    def _scan(self, session, lo, hi, cols, batch_size):
        for row in session.execute(self._range_query(lo, hi)):
            get = (
                row.get
                if isinstance(row, dict)
                else lambda k, r=row: getattr(r, k)
            )
            cols["latitude"].append(float(get("latitude")))
            cols["longitude"].append(float(get("longitude")))
            cols["user_id"].append(get("user_id"))
            cols["source"].append(get("source"))
            cols["timestamp"].append(get("timestamp"))
            if len(cols["latitude"]) >= batch_size:
                yield _finalize(cols)
                for v in cols.values():
                    v.clear()

    @_count_rows("cassandra")
    def batches(self, batch_size: int = DEFAULT_BATCH) -> Iterator[dict]:
        session, cluster = self._session()
        try:
            cols = {k: [] for k in COLUMNS}
            for _, (lo, hi) in self.my_ranges():
                yield from self._scan(session, lo, hi, cols, batch_size)
            if cols["latitude"]:
                yield _finalize(cols)
        finally:
            if cluster is not None:
                cluster.shutdown()

    def range_batches(self, index: int,
                      batch_size: int = DEFAULT_BATCH) -> Iterator[dict]:
        """Re-read exactly token range ``index`` (deterministic
        re-execution of one failed shard)."""
        lo, hi = token_ranges(self.config.n_ranges)[index]
        session, cluster = self._session()
        try:
            cols = {k: [] for k in COLUMNS}
            yield from self._scan(session, lo, hi, cols, batch_size)
            if cols["latitude"]:
                yield _finalize(cols)
        finally:
            if cluster is not None:
                cluster.shutdown()


@dataclasses.dataclass
class CosmosDBSource(Source):
    """CosmosDB ingest — the reference's alternative input path
    (reference heatmap.py:140-146: env-var host/key, database
    ``locationsdb``, collection ``locations``, selected when the
    Cassandra endpoint constant is falsy, heatmap.py:132).

    The Spark connector read the collection as one DataFrame; here the
    collection is scanned per **partition key range** — CosmosDB's
    physical shard unit and its analog of Cassandra token ranges — so
    ingest shards across hosts (``shard_index``/``shard_count``
    interleave ranges) and a failed range re-reads deterministically
    (``range_batches``).

    The azure-cosmos SDK is not baked into this image, so a
    ``client_factory`` must be injected: ``client_factory() ->
    client`` where ``client.partition_key_range_ids() -> [str]`` (may
    return ``[None]`` for single-range collections) and
    ``client.query_items(sql, partition_key_range_id=...) -> iterable
    of row dicts`` with the reference column names. A thin adapter
    over an ``azure.cosmos.ContainerProxy`` satisfies this: range ids
    from ``read_partition_key_ranges``, items from ``query_items``
    (the SDK pages transparently through its iterator).
    """

    config: CassandraConfig = dataclasses.field(default_factory=CassandraConfig)
    client_factory: object = None
    shard_index: int = 0
    shard_count: int = 1

    #: The reference reads whole documents; project just the point
    #: columns (SQL API shape).
    QUERY = ("SELECT c.latitude, c.longitude, c.user_id, c.source, "
             "c.timestamp FROM c")

    def __post_init__(self):
        if self.shard_count < 1 or not (0 <= self.shard_index < self.shard_count):
            raise ValueError(
                f"invalid shard assignment: shard_index={self.shard_index} "
                f"shard_count={self.shard_count} (need 0 <= index < count)"
            )

    def _client(self):
        cfg = self.config
        host = os.environ.get(cfg.cosmosdb_host_env)
        key = os.environ.get(cfg.cosmosdb_key_env)
        if self.client_factory is not None:
            return self.client_factory()
        if not host or not key:
            raise ConfigError(
                f"CosmosDB ingest needs ${cfg.cosmosdb_host_env} and "
                f"${cfg.cosmosdb_key_env} (reference heatmap.py:141-142) "
                "or an injected client_factory"
            )
        raise ConfigError(
            "CosmosDB ingest requires the azure-cosmos SDK, which is not "
            "available in this image; inject client_factory=... (see the "
            "class docstring for the adapter contract) or use "
            "CSV/JSONL/Parquet sources"
        )

    def _scan_range(self, client, range_id, cols, batch_size):
        for row in client.query_items(
            self.QUERY, partition_key_range_id=range_id
        ):
            cols["latitude"].append(float(row["latitude"]))
            cols["longitude"].append(float(row["longitude"]))
            cols["user_id"].append(row.get("user_id", ""))
            cols["source"].append(row.get("source", ""))
            cols["timestamp"].append(row.get("timestamp"))
            if len(cols["latitude"]) >= batch_size:
                yield _finalize(cols)
                for v in cols.values():
                    v.clear()

    def my_range_ids(self, client) -> list:
        ids = list(client.partition_key_range_ids())
        return [
            r for i, r in enumerate(ids)
            if i % self.shard_count == self.shard_index
        ]

    @_count_rows("cosmosdb")
    def batches(self, batch_size: int = DEFAULT_BATCH) -> Iterator[dict]:
        client = self._client()
        cols = {k: [] for k in COLUMNS}
        for range_id in self.my_range_ids(client):
            yield from self._scan_range(client, range_id, cols, batch_size)
        if cols["latitude"]:
            yield _finalize(cols)

    def range_batches(self, range_id,
                      batch_size: int = DEFAULT_BATCH) -> Iterator[dict]:
        """Re-read exactly one partition key range (deterministic
        re-execution of a failed ingest shard)."""
        client = self._client()
        cols = {k: [] for k in COLUMNS}
        yield from self._scan_range(client, range_id, cols, batch_size)
        if cols["latitude"]:
            yield _finalize(cols)


def open_source(spec: str, read_value: bool | None = None, **kwargs) -> Source:
    """Parse a CLI source spec into a Source.

    Specs: ``synthetic:N`` (optionally ``synthetic:N:seed``),
    ``csv:PATH``, ``jsonl:PATH``, ``parquet:PATH``,
    ``cassandra:[ENDPOINT]``. Extension sniffing for bare paths.

    ``read_value`` controls the optional per-point weight column on the
    file sources that support one (CSV/JSONL/Parquet): None = auto
    (read it when present), False = ignore it (count-only consumers
    keep the native CSV fast path). Sources without a value concept
    ignore the option."""
    kind, _, rest = spec.partition(":")
    if kind == "synthetic":
        parts = rest.split(":") if rest else ["1000000"]
        n = int(parts[0])
        seed = int(parts[1]) if len(parts) > 1 else 0
        return SyntheticSource(n=n, seed=seed, **kwargs)
    if kind == "csv":
        return CSVSource(rest, read_value=read_value, **kwargs)
    if kind == "jsonl":
        return JSONLSource(rest, read_value=read_value, **kwargs)
    if kind == "parquet":
        return ParquetSource(rest, read_value=read_value, **kwargs)
    if kind == "cassandra":
        cfg = CassandraConfig(endpoint=rest or None)
        if not cfg.endpoint:
            # The reference picks CosmosDB when the endpoint constant is
            # falsy (reference heatmap.py:132).
            return CosmosDBSource(config=cfg, **kwargs)
        return CassandraSource(config=cfg, **kwargs)
    if kind == "cosmosdb":
        return CosmosDBSource(**kwargs)
    if kind == "hmpb":
        from heatmap_tpu.io.hmpb import HMPBDirSource, HMPBSource

        if os.path.isdir(rest):
            return HMPBDirSource(rest, **kwargs)
        return HMPBSource(rest, **kwargs)
    # Bare path: sniff the extension.
    if spec.endswith(".csv"):
        return CSVSource(spec, read_value=read_value, **kwargs)
    if spec.endswith((".jsonl", ".ndjson")):
        return JSONLSource(spec, read_value=read_value, **kwargs)
    if spec.endswith((".parquet", ".pq")):
        return ParquetSource(spec, read_value=read_value, **kwargs)
    if spec.endswith(".hmpb"):
        from heatmap_tpu.io.hmpb import HMPBSource

        return HMPBSource(spec, **kwargs)
    raise ValueError(f"unrecognized source spec {spec!r}")
