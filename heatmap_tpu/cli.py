"""Command-line entry: the TPU-native ``submit-heatmap``.

A real flag system replacing the reference's three config mechanisms —
hard-coded module constants (reference heatmap.py:16-23), env vars
(reference heatmap.py:141-142), and spark-submit ``--conf`` flags
(reference submit-heatmap:7-14). ``--backend`` selects the device
platform (the BASELINE.json ``--backend=tpu`` switch); source/sink
specs replace the Cassandra/CosmosDB constants.

Subcommands:

- ``run``   — the batch job (reference batchMain, heatmap.py:152-158):
              source -> cascade -> blob sink.
- ``tiles`` — dense-window binning -> z/x/y PNG tile tree (new egress
              surface, BASELINE.md config 3).
- ``info``  — print resolved config + device inventory as JSON.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _add_backend_flags(p):
    p.add_argument(
        "--backend",
        choices=("tpu", "cpu"),
        default="tpu",
        help="device platform; tpu = whatever accelerator JAX finds "
        "(default), cpu = force host platform",
    )
    p.add_argument(
        "--device-timeout", type=float, default=180.0,
        help="seconds to wait for the accelerator to answer before "
        "failing the command (0 disables the probe; a dead relay "
        "otherwise hangs backend init forever). Generous by default: "
        "relay round-trip cost varies 2-5x day to day, and a job "
        "false-failed on a slow-but-alive backend costs more than a "
        "longer wait on a dead one",
    )
    p.add_argument(
        "--no-x64",
        action="store_true",
        help="keep JAX in 32-bit mode (the composite-key cascade needs "
        "x64; only the dense tiles path works without it)",
    )
    p.add_argument(
        "--chaos", default=None, metavar="SPEC",
        help="arm deterministic fault injection (heatmap_tpu.faults), "
        "e.g. 'seed=7,source.read=3x5,sink.write=p0.01'; also read "
        "from $HEATMAP_TPU_CHAOS (flag wins). See docs/robustness.md",
    )


def _init_backend(args):
    import jax

    from heatmap_tpu import faults

    faults.install_from_env(getattr(args, "chaos", None))
    if args.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if not args.no_x64:
        jax.config.update("jax_enable_x64", True)
    if (args.backend != "cpu" and getattr(args, "device_timeout", 0) > 0
            and not getattr(args, "multihost", False)):
        # NOTE the multihost exclusion: the probe initializes the LOCAL
        # backend, after which jax.distributed.initialize() fails
        # (parallel/multihost.py ordering contract) — pods fail loudly
        # on a dead relay inside distributed init anyway.
        # Fail FAST and loud when the accelerator is unreachable:
        # backend init blocks forever on a dead relay tunnel, which
        # turns "the device is down" into a silent multi-hour hang in
        # the middle of a job submission. No silent CPU fallback here —
        # a job the user pinned to tpu must not quietly produce CPU
        # results (bench.py's fallback is different: an artifact must
        # always exist). The probe thread is daemonized; if it never
        # returns it dies with the process.
        import threading

        probe_ok = threading.Event()

        def _probe():
            jax.devices()
            probe_ok.set()

        t = threading.Thread(target=_probe, daemon=True)
        t.start()
        t.join(timeout=args.device_timeout)
        if not probe_ok.is_set():
            raise SystemExit(
                f"accelerator backend did not answer within "
                f"{args.device_timeout:.0f}s (relay/tunnel down?) — "
                "retry later, raise --device-timeout, or run with "
                "--backend cpu"
            )
    return jax


def _sink_spec(spec: str) -> str:
    """argparse type= wrapper: reject a typo'd --output kind at parse
    time (one-line error listing the valid kinds) instead of after
    backend init and ingest."""
    from heatmap_tpu.io.sinks import validate_sink_spec

    try:
        return validate_sink_spec(spec)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e)) from e


def _add_run_flags(p):
    p.add_argument("--input", required=True,
                   help="source spec: synthetic:N[:seed] | csv:P | jsonl:P "
                   "| parquet:P | hmpb:P | cassandra:[ENDPOINT] | cosmosdb:")
    p.add_argument("--output", default="jsonl:heatmaps.jsonl",
                   type=_sink_spec,
                   help="sink spec: jsonl:P | dir:P | memory: | "
                   "cassandra: | arrays:DIR (columnar per-level npz)")
    p.add_argument("--detail-zoom", type=int, default=21,
                   help="finest binning zoom (reference MAX_ZOOM_LEVEL + "
                   "DETAIL_ZOOM_DELTA = 21, heatmap.py:16-17,27)")
    p.add_argument("--min-detail-zoom", type=int, default=5,
                   help="cascade floor; detail levels run down to this+1 "
                   "(reference range(21, 5, -1), heatmap.py:109)")
    p.add_argument("--result-delta", type=int, default=5,
                   help="blob tiles are this many zooms coarser than "
                   "detail (reference DETAIL_ZOOM_DELTA, heatmap.py:16)")
    p.add_argument("--timespans", default="alltime",
                   help="comma list of alltime,year,month,day (reference "
                   "supports these but ships alltime-only, heatmap.py:62)")
    p.add_argument("--batch-size", type=int, default=1 << 20)
    p.add_argument("--max-points-in-flight", type=int, default=None,
                   metavar="N",
                   help="bound peak memory: run the cascade per chunk of "
                   "at most N points and merge per-level aggregates "
                   "(exact). Default: auto — sources estimated larger "
                   "than host RAM take the bounded path with a "
                   "RAM-derived chunk; 0 forces single-shot")
    p.add_argument("--merge-spill-dir", default=None, metavar="DIR",
                   help="bounded path only: spill per-chunk aggregates "
                   "to DIR and merge one level at a time at egress, "
                   "bounding the cross-chunk merge table too (for "
                   "near-unique-output shapes; exact results)")
    p.add_argument("--capacity", type=int, default=None,
                   help="unique-key capacity for the device cascade "
                   "(default: #emissions)")
    p.add_argument("--amplify-all", action="store_true",
                   help="reproduce the reference's 'all'-amplification "
                   "cascade quirk (SURVEY.md §8.1) for bit-parity")
    p.add_argument("--first-timespan-only", action="store_true",
                   help="reproduce the reference's early-return timespan "
                   "quirk (SURVEY.md §8.2)")
    p.add_argument("--cascade-backend", default="auto",
                   choices=("auto", "scatter", "partitioned"),
                   help="cascade reduction: auto (default — partitioned "
                   "MXU kernel for count jobs, 1.8x the scatter kernel "
                   "on chip; scatter for weighted jobs), or pin either "
                   "backend explicitly (see PERF_NOTES.md round 5)")
    p.add_argument("--weighted", action="store_true",
                   help="sum the source's per-point 'value' column into "
                   "the heatmaps instead of counting points (works with "
                   "--fast on HMPB inputs converted from a weighted "
                   "source, and with --max-points-in-flight)")
    p.add_argument("--weight-bound", type=int, default=None, metavar="W",
                   help="declare the bounded-integer weight contract "
                   "(every 'value' an integer in [0, W]) — unlocks the "
                   "partitioned cascade backend for weighted jobs; "
                   "violations surface as capacity overflow, never a "
                   "rounded sum")
    p.add_argument("--data-parallel", choices=("auto", "on", "off"),
                   default="auto",
                   help="cascade data-parallelism over this process's "
                   "local devices: auto (default) engages past "
                   "--dp-min-emissions when >1 device is visible; on "
                   "forces the mesh path at any size; off pins the "
                   "single-device cascade. Blobs are identical either "
                   "way (counts bit-exact; fractional weighted sums up "
                   "to f64 summation order)")
    p.add_argument("--dp-merge", choices=("replicated", "prefix"),
                   default="replicated",
                   help="data-parallel cascade merge: replicated "
                   "(default; every device re-reduces the gathered "
                   "partials) or prefix (coarse-prefix all_to_all "
                   "regroup; each device merges and rolls up only its "
                   "keyspan range — O(uniques/k) per stage, the shape "
                   "for unique-heavy data). Blobs identical either way")
    p.add_argument("--dp-min-emissions", type=int, default=None,
                   metavar="N",
                   help="auto-DP engagement threshold (emissions per "
                   "cascade call; default batch.AUTO_DP_MIN_EMISSIONS "
                   "= 2^18, calibrated on a CPU mesh only). Measure "
                   "the real crossover on your hardware with the "
                   "docs/OPERATIONS.md 'Calibrating auto-DP' recipe; "
                   "auto mode only")
    p.add_argument("--spatial-partition", choices=("auto", "morton", "off"),
                   default="auto",
                   help="Morton-range sharding of the data-parallel "
                   "cascade: each device owns one contiguous Z-order "
                   "code range and the cross-device merge shrinks to "
                   "boundary tiles only (docs/parallel-partitioning.md). "
                   "auto (default) plans ranges when the mesh engages "
                   "on real volume; morton forces it; off pins the "
                   "uniform round-robin dispatch. Blobs byte-identical "
                   "in every mode")
    p.add_argument("--dispatch", choices=("auto", "gspmd", "shard_map"),
                   default="auto",
                   help="how the data-parallel cascade is dispatched: "
                   "gspmd runs the whole cascade (routing, rollup, "
                   "boundary merge, egress ordering) as ONE compiled "
                   "program over a NamedSharding mesh with no host "
                   "round-trips (docs/gspmd.md); shard_map keeps the "
                   "per-stage host-routed dispatch as a differential-"
                   "testing oracle. auto (default) picks gspmd wherever "
                   "a program exists. Blobs byte-identical either way")
    p.add_argument("--fast", action="store_true",
                   help="force the integer-only native-decoder path "
                   "(csv/hmpb sources; dated timespans use the i64 "
                   "epoch-ms column; needs the native/ build for csv). "
                   "Eligible sources route here AUTOMATICALLY — this "
                   "flag only turns silent fallback into a hard error")
    p.add_argument("--no-fast", action="store_true",
                   help="disable the automatic fast-path routing and "
                   "run the generic per-row ingest")
    p.add_argument("--checkpoint-dir", default=None,
                   help="checkpoint ingest progress here and resume from "
                   "the latest checkpoint on rerun")
    p.add_argument("--checkpoint-every", type=int, default=8,
                   help="checkpoint every N source batches")
    p.add_argument("--profile", default=None, metavar="LOGDIR",
                   help="capture a jax.profiler trace into LOGDIR and "
                   "print the span/throughput report to stderr")
    p.add_argument("--metrics-dir", default=None, metavar="DIR",
                   help="enable the metrics registry and write a "
                   "Prometheus-text dump to DIR/metrics.prom at job end "
                   "(docs/observability.md)")
    p.add_argument("--events", default=None, metavar="PATH",
                   help="append structured run events to PATH (JSONL: "
                   "run_start manifest, stage_end timings with backend "
                   "attribution, backend_resolved, device_memory, "
                   "run_end — docs/observability.md)")
    p.add_argument("--report", nargs="?", const="run_report.json",
                   default=None, metavar="PATH",
                   help="fold tracer + metrics + events into a "
                   "run_report.json artifact at PATH (default "
                   "run_report.json) and print the span/throughput "
                   "table to stderr — no --profile required")
    p.add_argument("--multihost", action="store_true",
                   help="SPMD multi-host job: jax.distributed init, "
                   "per-process ingest shard (connector ranges or batch "
                   "slices), DCN blob merge, process 0 writes the sink; "
                   "single-process falls through to the plain job")
    p.add_argument("--multihost-egress",
                   choices=("auto", "gather", "sharded"), default="auto",
                   help="gather (the auto default): full DCN merge, "
                   "process 0 writes. sharded: blob keys partition "
                   "across processes and EVERY host writes its own sink "
                   "shard (path sinks get a per-host suffix "
                   "automatically) — the scalable reducer-write path; "
                   "required for columnar sinks on pods")
    p.add_argument("--heartbeat-deadline", type=float, default=None,
                   metavar="S",
                   help="arm straggler detection: at each multihost "
                   "phase boundary, a host whose heartbeat is older "
                   "than S seconds raises a typed StragglerTimeout "
                   "(docs/robustness.md) instead of hanging the next "
                   "collective")
    p.add_argument("--on-straggler", choices=("raise", "reassign"),
                   default="raise",
                   help="what a straggler timeout means: raise (the "
                   "default — job dies with the typed error) or "
                   "reassign (elastic execution: the stale host's "
                   "unfinished shards re-run on survivors from the "
                   "--elastic-dir lineage manifest, output "
                   "byte-identical to an unfailed run; needs a "
                   "columnar arrays: output)")
    p.add_argument("--elastic-dir", default=None, metavar="DIR",
                   help="shard-lineage manifest root for "
                   "--on-straggler reassign: completed shards persist "
                   "their partial pyramid here atomically, so finished "
                   "work survives a crash and re-runs are exactly-once "
                   "by shard hash")
    p.add_argument("--elastic-hosts", type=int, default=None, metavar="K",
                   help="simulated host count for elastic execution on "
                   "a single process (default 2); real multi-process "
                   "runs use one host per process")
    _add_trace_flags(p)


def _add_trace_flags(p):
    """--trace-out / --trace-sample / --slo, shared by run, update and
    serve (docs/observability.md)."""
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="enable hierarchical span tracing and export "
                   "the span trees as Chrome/Perfetto trace-event JSON "
                   "to PATH at exit (load in chrome://tracing, "
                   "ui.perfetto.dev, or tools/trace_analyze.py)")
    p.add_argument("--trace-sample", type=float, default=1.0, metavar="P",
                   help="probability a new trace root is sampled "
                   "(decided once per root — e.g. per serve request; "
                   "default 1.0 records every trace)")
    p.add_argument("--slo", action="append", default=None, metavar="SPEC",
                   help="declare an SLO as NAME:KIND:k=v,... (kinds: "
                   "latency, error_rate, staleness; repeatable). "
                   "Error-budget burn rates fold into /healthz, the "
                   "run report, and slo_breach events")
    p.add_argument("--flight-recorder-spans", type=int, default=256,
                   metavar="N",
                   help="flight-recorder ring capacity: last N completed "
                   "spans per subsystem kept regardless of head "
                   "sampling, promoted into the trace on errors/5xx/"
                   "tail latency (0 disables the recorder; it only "
                   "arms when --trace-out, --events or --incident-dir "
                   "is also given — docs/observability.md)")
    p.add_argument("--incident-dir", default=None, metavar="DIR",
                   help="flush self-contained incident bundles here on "
                   "failure edges (SLO breach, shed, fault storm, "
                   "degraded-enter, uncaught exception); rate-limited "
                   "and pruned age-wins")
    p.add_argument("--tail-latency-ms", type=float, default=None,
                   metavar="MS",
                   help="tail-based retention threshold: any trace "
                   "slower than this is promoted from the flight "
                   "recorder into the trace as if head-sampled")
    p.add_argument("--telemetry-sample-interval", type=float, default=0.0,
                   metavar="SEC",
                   help="background telemetry sampler cadence: every "
                   "SEC seconds the obs registry is snapshotted into "
                   "the in-process time-series tiers that back "
                   "/series, /dashboard, and incident-bundle history "
                   "(docs/observability.md). 0 (the default) disables "
                   "the sampler entirely — zero threads, zero hot-path "
                   "cost")
    p.add_argument("--watch", action="append", default=None, metavar="SPEC",
                   help="watch a telemetry series for anomalies as "
                   "NAME:k=v,... (params: z, alpha, min_count, "
                   "clear_ratio; repeatable), e.g. "
                   "'ingest_lag_seconds:z=6'. Each rising edge emits "
                   "one anomaly_detected event and triggers an "
                   "incident bundle with the surrounding history "
                   "embedded; requires --telemetry-sample-interval > 0")


def _setup_tracing(args):
    """Wire --trace-out/--trace-sample/--slo; returns the live
    TraceCollector (None with tracing off)."""
    from heatmap_tpu import obs

    collector = None
    if getattr(args, "trace_out", None):
        try:
            collector = obs.enable_tracing(sample=args.trace_sample)
        except ValueError as e:
            raise SystemExit(f"--trace-sample: {e}") from e
    if getattr(args, "slo", None):
        try:
            obs.install_specs(args.slo)
        except ValueError as e:
            raise SystemExit(f"--slo: {e}") from e
    # Flight recorder + incident bundles. The recorder arms only when
    # some telemetry surface exists to promote/flush into, so a plain
    # run keeps every obs hook at None (blob byte-equality pinned by
    # tests/test_obs.py).
    spans = getattr(args, "flight_recorder_spans", 0) or 0
    if spans < 0:
        raise SystemExit(f"--flight-recorder-spans {spans}: must be >= 0")
    incident_dir = getattr(args, "incident_dir", None)
    armed = (collector is not None or incident_dir
             or getattr(args, "events", None))
    if spans and armed:
        tail_ms = getattr(args, "tail_latency_ms", None)
        if tail_ms is not None and tail_ms <= 0:
            raise SystemExit(
                f"--tail-latency-ms {tail_ms}: must be positive")
        obs.recorder.install(obs.FlightRecorder(
            max_spans=spans,
            tail_latency_s=None if tail_ms is None else tail_ms / 1000.0))
    if incident_dir:
        obs.incident.set_manager(obs.IncidentManager(incident_dir))
    # Telemetry sampler + anomaly watch list. Interval 0 (the default)
    # arms nothing: no store installed, no thread started, so the
    # sampler-off path is byte-identical to a build without this
    # subsystem (pinned in tests/test_timeseries.py).
    interval = getattr(args, "telemetry_sample_interval", 0.0) or 0.0
    if interval < 0:
        raise SystemExit(f"--telemetry-sample-interval {interval}: "
                         "must be >= 0")
    watches = getattr(args, "watch", None) or []
    if watches and not interval:
        raise SystemExit("--watch requires --telemetry-sample-interval "
                         "> 0 (detectors score sampler ticks)")
    if interval:
        from heatmap_tpu.obs import anomaly, timeseries

        engine = None
        if watches:
            try:
                specs = [anomaly.parse_watch_spec(s) for s in watches]
            except ValueError as e:
                raise SystemExit(f"--watch: {e}") from e
            engine = anomaly.AnomalyEngine(specs)
            anomaly.set_engine(engine)
        spill_dir = (os.path.join(incident_dir, "telemetry")
                     if incident_dir else None)
        timeseries.arm(interval, engine=engine, spill_dir=spill_dir)
    return collector


def _export_trace(args, collector):
    """End-of-job obs teardown: every command's exit path funnels
    through here, so the telemetry sampler is stopped (with a final
    crash-safe spill) before the trace export — both no-op when the
    respective subsystem was never armed."""
    from heatmap_tpu.obs import timeseries

    timeseries.shutdown()
    if collector is None:
        return
    n = collector.export_chrome(args.trace_out)
    line = {"trace_out": args.trace_out, "span_events": n,
            "dropped": collector.dropped}
    print(json.dumps(line), file=sys.stderr)


def _fail_telemetry(root_span, error):
    """Uncaught job exception: tail-promote the failed root's tree out
    of the flight recorder and flush an exception incident bundle.
    Both no-op when nothing is installed. Must run before end_span on
    the root so the root rides the live-forward path."""
    from heatmap_tpu.obs import incident, recorder

    recorder.maybe_promote(root_span, error=True)
    incident.trigger("exception", detail=repr(error))


def cmd_run(args) -> int:
    from heatmap_tpu.pipeline.timespan import VALID_TYPES

    requested = tuple(t.strip() for t in args.timespans.split(",") if t.strip())
    bad = [t for t in requested if t not in VALID_TYPES]
    if bad:
        raise SystemExit(
            f"--timespans: unknown type(s) {bad}; valid: {', '.join(VALID_TYPES)}"
        )
    _init_backend(args)
    import contextlib

    from heatmap_tpu.io import open_sink, open_source
    from heatmap_tpu.pipeline import (
        BatchJobConfig,
        run_job,
        run_job_fast,
        run_job_resumable,
    )
    from heatmap_tpu.utils.trace import get_tracer, jax_profile

    try:
        config = BatchJobConfig(
            detail_zoom=args.detail_zoom,
            min_detail_zoom=args.min_detail_zoom,
            result_delta=args.result_delta,
            timespans=requested,
            amplify_all=args.amplify_all,
            first_timespan_only=args.first_timespan_only,
            capacity=args.capacity,
            weighted=args.weighted,
            weight_bound=args.weight_bound,
            cascade_backend=args.cascade_backend,
            data_parallel={"auto": None, "on": True, "off": False}[
                args.data_parallel],
            dp_merge=args.dp_merge,
            dp_min_emissions=args.dp_min_emissions,
            spatial_partition=args.spatial_partition,
            dispatch=args.dispatch,
        )
    except ValueError as e:
        raise SystemExit(str(e)) from e
    if args.multihost_egress != "auto" and not args.multihost:
        # A forgotten --multihost would otherwise run the full plain
        # job on EVERY host of a per-host launch script, with all of
        # them writing the same output path.
        raise SystemExit("--multihost-egress requires --multihost")
    if not args.multihost and (args.on_straggler != "raise"
                               or args.elastic_dir or args.elastic_hosts
                               or args.heartbeat_deadline is not None):
        raise SystemExit("--heartbeat-deadline / --on-straggler / "
                         "--elastic-dir / --elastic-hosts require "
                         "--multihost")
    if args.on_straggler == "reassign" and not args.elastic_dir:
        raise SystemExit("--on-straggler reassign needs --elastic-dir "
                         "(the shard-lineage manifest is what makes "
                         "failover re-execution exactly-once)")
    if args.merge_spill_dir and args.checkpoint_dir:
        # The spill merge lives on the bounded path; checkpointing
        # never routes there — ignoring the flag would quietly run the
        # unbounded in-RAM merge the operator asked to avoid.
        # (--multihost composes: each process's bounded slice ingest
        # takes the same spill knob, run_job_multihost validates.)
        raise SystemExit("--merge-spill-dir applies to the bounded "
                         "(chunked) path only; it cannot combine with "
                         "--checkpoint-dir")
    # 0 means "explicitly single-shot", which composes with both
    # checkpointing and multihost; only a positive bound conflicts.
    if args.max_points_in_flight and args.checkpoint_dir:
        raise SystemExit("--max-points-in-flight and --checkpoint-dir are "
                         "mutually exclusive (chunk boundaries are not "
                         "batch boundaries)")
    if args.multihost and (args.fast or args.checkpoint_dir):
        raise SystemExit("--multihost runs the standard job path only "
                         "(not --fast / --checkpoint-dir); "
                         "--max-points-in-flight composes (each process "
                         "streams its slice through the bounded path)")
    fast_source = None
    if args.fast and args.no_fast:
        raise SystemExit("--fast and --no-fast are mutually exclusive")
    if args.fast:
        # Resolve through open_source so bare paths and prefixed specs
        # behave identically to every other subcommand.
        from heatmap_tpu.io.hmpb import HMPBDirSource, HMPBSource
        from heatmap_tpu.io.sources import CSVSource

        src = open_source(args.input, read_value=False)
        if isinstance(src, CSVSource):
            fast_source = src.path
        elif isinstance(src, (HMPBSource, HMPBDirSource)):
            fast_source = src
        else:
            raise SystemExit(
                f"--fast needs a csv or hmpb source, got {args.input!r}"
            )
    elif (not args.no_fast and not args.multihost
          and not args.checkpoint_dir):
        # AUTO fast-path routing: the default ingest should never pay
        # per-row Python when the native/mmap path produces identical
        # blobs (equality pinned by tests/test_cli.py
        # test_run_fast_csv_matches_plain and tests/test_pipeline.py
        # weighted-HMPB tests). Conservative by construction — only
        # configurations those tests cover switch over; --checkpoint-dir
        # stays on the standard resumable path so reruns never change
        # an existing checkpoint's format mid-flight. --no-fast opts
        # out; --fast makes ineligibility a hard error instead.
        # Sniff the spec kind BEFORE constructing anything: opening is
        # not free (an .hmpb probe header-parses and mmaps the whole
        # file), so ineligible kinds (synthetic:, jsonl:, ...) never pay
        # for a probe, and a probe-opened source becomes the job source
        # on every run that proceeds.
        kind = args.input.partition(":")[0]
        is_csv = kind == "csv" or args.input.endswith(".csv")
        is_hmpb = kind == "hmpb" or args.input.endswith(".hmpb")
        if is_csv and not args.weighted:
            try:
                from heatmap_tpu.native import parse_csv_batches  # noqa: F401
            except ImportError:
                pass  # native decoder unavailable: per-row path
            else:
                from heatmap_tpu.io.sources import CSVSource

                src = open_source(args.input, read_value=False)
                if isinstance(src, CSVSource):
                    fast_source = src.path
                src.close()  # only the path is kept either way
        elif is_hmpb:
            from heatmap_tpu.io.hmpb import HMPBDirSource, HMPBSource

            src = open_source(args.input, read_value=False)
            if isinstance(src, (HMPBSource, HMPBDirSource)) and (
                    not args.weighted or getattr(src, "has_value", False)):
                fast_source = src
            else:
                # Probe result discarded (e.g. weighted without a value
                # column): unmap now — the standard path re-opens the
                # input itself.
                src.close()
    if args.multihost:
        # Must run BEFORE anything that initializes the local backend —
        # the profiler's start_trace does — or jax.distributed.initialize
        # fails and every host silently runs the whole job alone.
        from heatmap_tpu.parallel import initialize

        initialize()
    output_spec = args.output
    if args.multihost and args.multihost_egress == "sharded":
        # Sharded egress: every process writes its own shard, so
        # path-backed sinks get this process's derived path (after
        # distributed init so process_index is final).
        import jax

        from heatmap_tpu.io.sinks import per_process_sink_spec

        output_spec = per_process_sink_spec(args.output, jax.process_index())
    # Telemetry (all opt-in; with every flag off the job path is
    # untouched and blobs are byte-identical — pinned by
    # tests/test_obs.py). --events installs the process event log,
    # --metrics-dir/--report enable the registry; the run report folds
    # whatever was collected at the end.
    telemetry = bool(args.metrics_dir or args.events
                     or args.report is not None)
    ev_log = None
    if telemetry:
        from heatmap_tpu import obs

        obs.enable_metrics(True)
        if args.events:
            ev_log = obs.EventLog(args.events)
            obs.set_event_log(ev_log)
            import dataclasses as _dc

            manifest = {k: (list(v) if isinstance(v, tuple) else v)
                        for k, v in _dc.asdict(config).items()}
            obs.emit("run_start", config=manifest, backend=args.backend,
                     devices=obs.device_topology(), argv=sys.argv[1:])
    from heatmap_tpu.obs import tracing as tracing_mod

    collector = _setup_tracing(args)
    root_span = tracing_mod.begin_span("run")
    t0 = time.perf_counter()
    prof = jax_profile(args.profile) if args.profile else contextlib.nullcontext()
    job_error = None
    blobs = None
    try:
        with prof:
            with open_sink(output_spec) as sink:
                if fast_source is not None:
                    blobs = run_job_fast(
                        fast_source, sink, config,
                        batch_size=args.batch_size,
                        checkpoint_dir=args.checkpoint_dir,
                        checkpoint_every=args.checkpoint_every,
                        max_points_in_flight=args.max_points_in_flight,
                        merge_spill_dir=args.merge_spill_dir,
                    )
                elif args.checkpoint_dir:
                    blobs = run_job_resumable(
                        open_source(args.input, read_value=args.weighted),
                        args.checkpoint_dir, sink,
                        config, batch_size=args.batch_size,
                        checkpoint_every=args.checkpoint_every,
                    )
                elif args.multihost:
                    from heatmap_tpu.parallel import run_job_multihost

                    blobs = run_job_multihost(
                        open_source(args.input, read_value=args.weighted),
                        sink, config, batch_size=args.batch_size,
                        max_points_in_flight=args.max_points_in_flight,
                        egress=args.multihost_egress,
                        merge_spill_dir=args.merge_spill_dir,
                        heartbeat_deadline_s=args.heartbeat_deadline,
                        on_straggler=args.on_straggler,
                        elastic_dir=args.elastic_dir,
                        elastic_hosts=args.elastic_hosts,
                    )
                else:
                    blobs = run_job(open_source(args.input,
                                                read_value=args.weighted),
                                    sink, config,
                                    batch_size=args.batch_size,
                                    max_points_in_flight=args.max_points_in_flight,
                                    merge_spill_dir=args.merge_spill_dir)
    except BaseException as e:  # noqa: BLE001 — run_end must record it
        _fail_telemetry(root_span, e)
        if not telemetry:
            tracing_mod.end_span(root_span)
            _export_trace(args, collector)
            raise
        job_error = e
    dt = time.perf_counter() - t0
    tracing_mod.end_span(root_span)
    if args.profile:
        print(get_tracer().format_report(), file=sys.stderr)
    if telemetry:
        from heatmap_tpu import obs

        obs.sample_device_memory()
        if ev_log is not None:
            end = {"status": "error" if job_error is not None else "ok",
                   "seconds": round(dt, 3)}
            if job_error is not None:
                end["error"] = repr(job_error)
            elif isinstance(blobs, dict) and str(
                    blobs.get("egress", "")).startswith("levels"):
                end["levels"] = blobs["levels"]
                end["rows"] = blobs["rows"]
            else:
                end["blobs"] = len(blobs)
                end["checksum"] = obs.blob_checksum(blobs)
            obs.emit("run_end", **end)
            obs.set_event_log(None)
            ev_log.close()
        if args.metrics_dir:
            obs.get_registry().write_prometheus(
                os.path.join(args.metrics_dir, "metrics.prom"))
        if args.report is not None:
            report = obs.build_run_report(
                tracer=get_tracer(), registry=obs.get_registry(),
                events_path=args.events)
            obs.write_run_report(args.report, report)
            print(obs.format_run_report(report), file=sys.stderr)
        if job_error is not None:
            _export_trace(args, collector)
            raise job_error
    _export_trace(args, collector)
    summary = {"seconds": round(dt, 3), "output": output_spec,
               "ingest": "fast" if fast_source is not None else "standard"}
    if isinstance(blobs, dict) and str(
            blobs.get("egress", "")).startswith("levels"):
        # "levels" (columnar) and "levels-sharded" (multihost columnar)
        summary["levels"] = blobs["levels"]
        summary["rows"] = blobs["rows"]
    else:
        summary["blobs"] = len(blobs)
    print(json.dumps(summary))
    return 0


def _scan_bounds(source, batch_size):
    """Data bounding box, padded 5%, or None for no finite coordinates.

    One pre-pass over the source (the fixed flag defaults cover the US
    Pacific Northwest; data elsewhere would silently bin to zero
    tiles). Sources iterate deterministically, so re-reading is safe.
    Raw lat/lon columns only — no load_columns (its per-row
    user_id/timestamp lists would double the job's Python cost for a
    min/max; background rows merely widen the covering window
    harmlessly). NaN coordinates are skipped; window_from_bounds
    clamps to the Mercator-valid band itself.
    """
    import numpy as np

    lat_lo = lon_lo = float("inf")
    lat_hi = lon_hi = float("-inf")
    for batch in source.batches(batch_size):
        lat = np.asarray(batch["latitude"], np.float64)
        lon = np.asarray(batch["longitude"], np.float64)
        if len(lat) == 0:
            continue
        # Finite coordinates only: NaN AND ±inf rows must not poison
        # the bbox (CSV float() happily parses "inf"); the projection
        # clamps latitude but an infinite longitude would overflow.
        finite = np.isfinite(lat) & np.isfinite(lon)
        if not finite.any():
            continue
        flat, flon = lat[finite], lon[finite]
        lat_lo = min(lat_lo, float(flat.min()))
        lat_hi = max(lat_hi, float(flat.max()))
        lon_lo = min(lon_lo, float(flon.min()))
        lon_hi = max(lon_hi, float(flon.max()))
    if lat_lo > lat_hi:
        return None
    pad_lat = max(0.05 * (lat_hi - lat_lo), 1e-3)
    pad_lon = max(0.05 * (lon_hi - lon_lo), 1e-3)
    return (lat_lo - pad_lat, lat_hi + pad_lat,
            lon_lo - pad_lon, lon_hi + pad_lon)


def cmd_tiles(args) -> int:
    if args.zoom < args.pixel_delta:
        raise SystemExit(
            f"--zoom {args.zoom} must be >= --pixel-delta {args.pixel_delta} "
            "(tile zoom = zoom - pixel_delta)"
        )
    if args.splat and (args.splat < 0 or args.splat % 2 == 0):
        raise SystemExit(f"--splat {args.splat}: kernel size must be odd")
    if args.sigma is not None and not args.sigma > 0:
        raise SystemExit(f"--sigma {args.sigma}: must be positive")
    _init_backend(args)
    import jax.numpy as jnp
    import numpy as np

    from heatmap_tpu.io import PNGTileSink, open_source
    from heatmap_tpu.ops import bin_points_window, window_from_bounds
    from heatmap_tpu.pipeline import load_columns

    proj_dtype = jnp.float32 if args.no_x64 else jnp.float64
    # Count-only runs skip the value column so weighted CSVs keep the
    # native fast parser; --weighted reads it (auto would too, but the
    # explicit hint makes the missing-column error come from this
    # command, not a parser heuristic).
    source = open_source(args.input, read_value=bool(args.weighted))
    if args.auto_bounds:
        bounds = _scan_bounds(source, args.batch_size)
        if bounds is None:
            print(json.dumps({"tiles": 0, "output": args.output}))
            return 0
        args.lat_min, args.lat_max, args.lon_min, args.lon_max = bounds
    window = window_from_bounds(
        (args.lat_min, args.lat_max),
        (args.lon_min, args.lon_max),
        zoom=args.zoom,
        align_levels=min(args.pixel_delta, args.zoom),
        pad_multiple=1 << args.pixel_delta,
    )
    raster = None
    t0 = time.perf_counter()
    for batch in source.batches(args.batch_size):
        cols = load_columns(batch)
        weights = None
        if args.weighted:
            if "value" not in cols:
                raise SystemExit(
                    "--weighted needs a 'value' column in the input "
                    "(CSV/JSONL/Parquet column named 'value')"
                )
            weights = jnp.asarray(cols["value"], jnp.float32)
        part = bin_points_window(
            jnp.asarray(cols["latitude"]),
            jnp.asarray(cols["longitude"]),
            window,
            weights=weights,
            proj_dtype=proj_dtype,
            backend=args.bin_backend,
        )
        raster = part if raster is None else raster + part
    if raster is None:
        print(json.dumps({"tiles": 0, "output": args.output}))
        return 0
    if args.splat:
        from heatmap_tpu.ops import gaussian_kernel_1d, splat_raster

        raster = splat_raster(
            raster, gaussian_kernel_1d(args.splat, args.sigma)
        )
    sink = PNGTileSink(args.output, pixel_delta=args.pixel_delta)
    n = sink.write_window(np.asarray(raster), window)
    dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "tiles": n,
                "tile_zoom": args.zoom - args.pixel_delta,
                "bounds": [round(args.lat_min, 6), round(args.lat_max, 6),
                           round(args.lon_min, 6), round(args.lon_max, 6)],
                "seconds": round(dt, 3),
                "output": args.output,
            }
        )
    )
    return 0


def _live_dir(args) -> str:
    """Root for runtime tile artifacts (the --live-dir knob): explicit
    flag > checkpoint dir > system tmp — never the CWD, so streaming
    runs and tests stop littering the working directory."""
    if getattr(args, "live_dir", None):
        return args.live_dir
    if getattr(args, "checkpoint_dir", None):
        return args.checkpoint_dir
    import tempfile

    return os.path.join(tempfile.gettempdir(), "heatmap-tpu")


def cmd_stream(args) -> int:
    if args.output is None:
        args.output = os.path.join(_live_dir(args), "live_tiles")
    if args.half_life <= 0:
        raise SystemExit(f"--half-life {args.half_life}: must be positive")
    if args.zoom < args.pixel_delta:
        raise SystemExit(
            f"--zoom {args.zoom} must be >= --pixel-delta {args.pixel_delta} "
            "(tile zoom = zoom - pixel_delta)"
        )
    if args.checkpoint_dir and args.checkpoint_every < 1:
        raise SystemExit(
            f"--checkpoint-every {args.checkpoint_every}: must be >= 1"
        )
    _init_backend(args)
    import jax.numpy as jnp
    import numpy as np

    from heatmap_tpu.io import PNGTileSink, open_source
    from heatmap_tpu.ops import window_from_bounds
    from heatmap_tpu.pipeline import load_columns
    from heatmap_tpu.streaming import HeatmapStream, StreamConfig
    from heatmap_tpu.utils import CheckpointManager

    if args.auto_bounds:
        # Needs a re-iterable (file) source; same file on resume gives
        # the same window (restore() rejects a shifted one).
        bounds = _scan_bounds(open_source(args.input, read_value=False),
                              args.batch_points)
        if bounds is None:
            print(json.dumps({"batches": 0, "stream_seconds": 0.0,
                              "live_mass": 0.0, "tiles": 0,
                              "seconds": 0.0, "output": args.output}))
            return 0
        args.lat_min, args.lat_max, args.lon_min, args.lon_max = bounds
    window = window_from_bounds(
        (args.lat_min, args.lat_max),
        (args.lon_min, args.lon_max),
        zoom=args.zoom,
        align_levels=min(args.pixel_delta, args.zoom),
        pad_multiple=1 << args.pixel_delta,
    )
    proj_dtype = jnp.float32 if args.no_x64 else jnp.float64
    config = StreamConfig(
        window=window,
        half_life_s=args.half_life,
        proj_dtype=proj_dtype,
        pad_to=args.batch_points,
        backend=args.bin_backend,
    )
    stream = HeatmapStream(config)
    mgr = None
    if args.checkpoint_dir:
        mgr = CheckpointManager(args.checkpoint_dir)
        if mgr.latest_step() is not None:
            stream.restore(mgr, weighted=args.weighted)
    t0 = time.perf_counter()
    resumed = stream.n_batches
    t_stream = stream.t or 0.0
    i = 0
    for batch in open_source(
        args.input, read_value=args.weighted,
    ).batches(args.batch_points):
        i += 1
        if i <= resumed:
            continue  # deterministic source replay up to the checkpoint
        cols = load_columns(batch)
        t_stream += args.interval
        weights = None
        if args.weighted:
            if "value" not in cols:
                raise SystemExit(
                    "--weighted needs a 'value' column in the input "
                    "(CSV/JSONL/Parquet column named 'value')"
                )
            weights = cols["value"]
        stream.update(cols["latitude"], cols["longitude"], t_stream,
                      weights=weights)
        if mgr is not None and stream.n_batches % args.checkpoint_every == 0:
            stream.checkpoint(mgr, weighted=args.weighted)
    if mgr is not None:
        stream.checkpoint(mgr, weighted=args.weighted)
    snap = stream.snapshot()  # one device->host copy, reused below
    n_tiles = 0
    if args.output:
        sink = PNGTileSink(args.output, pixel_delta=args.pixel_delta)
        n_tiles = sink.write_window(snap, window)
    print(json.dumps({
        "batches": stream.n_batches,
        "stream_seconds": stream.t,
        "live_mass": float(np.sum(snap)),
        "bounds": [round(args.lat_min, 6), round(args.lat_max, 6),
                   round(args.lon_min, 6), round(args.lon_max, 6)],
        "tiles": n_tiles,
        "seconds": round(time.perf_counter() - t0, 3),
        "output": args.output,
    }))
    return 0


def _parse_layers(arg: str | None):
    """``--layers name=user|timespan,...`` -> {name: selector} or None
    (= expose every slice + the 'default' alias)."""
    if not arg:
        return None
    layers = {}
    for part in arg.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, sel = part.partition("=")
        layers[name.strip()] = (sel if sep else name).strip()
    return layers or None


def _follow_stream(args, app):
    """Live mode: pump micro-batches from --follow-stream into a
    LiveLayer on a daemon thread; each tick invalidates only the cache
    keys of tiles the batch touched. Returns a stop() callback."""
    _init_backend(args)
    import threading

    import jax.numpy as jnp

    from heatmap_tpu.io import open_source
    from heatmap_tpu.ops import window_from_bounds
    from heatmap_tpu.pipeline import load_columns
    from heatmap_tpu.serve import LiveLayer
    from heatmap_tpu.streaming import HeatmapStream, StreamConfig

    window = window_from_bounds(
        (args.lat_min, args.lat_max),
        (args.lon_min, args.lon_max),
        zoom=args.zoom,
    )
    config = StreamConfig(
        window=window,
        half_life_s=args.half_life,
        proj_dtype=jnp.float32 if args.no_x64 else jnp.float64,
        pad_to=args.batch_points,
    )
    layer = LiveLayer(HeatmapStream(config), name=args.live_layer)
    app.attach_layer(args.live_layer, layer)
    done = threading.Event()

    def _pump():
        t_stream = 0.0
        source = open_source(args.follow_stream, read_value=False)
        for batch in source.batches(args.batch_points):
            if done.is_set():
                break
            cols = load_columns(batch)
            t_stream += args.interval
            keys = layer.tick(cols["latitude"], cols["longitude"], t_stream)
            app.cache.invalidate_keys(keys)
            if args.tick_seconds > 0:
                done.wait(args.tick_seconds)

    thread = threading.Thread(target=_pump, name="serve-stream", daemon=True)
    thread.start()

    def stop():
        done.set()
        thread.join(timeout=5)

    return stop


def cmd_serve(args) -> int:
    """Tile HTTP server over a batch egress artifact (docs/serving.md).

    Numpy-only unless --follow-stream is given: serving a finished job
    never initializes a jax backend, so the server stays up next to a
    dead accelerator relay.
    """
    from heatmap_tpu import faults, obs
    from heatmap_tpu.serve import ServeApp, TileCache, TileStore, make_server

    # serve skips _init_backend (numpy-only), so arm chaos here too.
    faults.install_from_env(getattr(args, "chaos", None))
    # /metrics is a first-class endpoint here, not an opt-in artifact.
    obs.enable_metrics(True)
    ev_log = None
    if args.events:
        ev_log = obs.EventLog(args.events)
        obs.set_event_log(ev_log)
    collector = _setup_tracing(args)
    if getattr(args, "fleet", None):
        if args.follow_stream:
            raise SystemExit("--fleet is incompatible with --follow-stream "
                             "(live layers are per-process state)")
        return _serve_fleet(args, collector, ev_log)
    ttl = args.ttl
    if args.follow_stream and not (ttl and ttl > 0):
        # Targeted invalidation only drops tiles a batch touched; decay
        # drifts every OTHER cached tile, so live mode needs its
        # staleness bounded by a finite TTL (serve/live.py).
        ttl = max(1.0, args.interval / 2)
    try:
        store = TileStore(args.store, layers=_parse_layers(args.layers))
    except (ValueError, OSError) as e:
        raise SystemExit(str(e)) from e
    cache = TileCache(max_bytes=args.cache_bytes,
                      ttl_s=ttl if (ttl and ttl > 0) else None)
    from heatmap_tpu.serve import degrade as degrade_mod

    try:
        controller = degrade_mod.controller_from_flags(
            getattr(args, "degrade", False),
            getattr(args, "degrade_dwell", 10.0),
            getattr(args, "degrade_hold", 30.0),
            getattr(args, "degrade_ladder", ""))
    except ValueError as e:
        raise SystemExit(f"--degrade-ladder: {e}") from e
    disk_cache = prewarm = None
    if getattr(args, "disk_cache", None):
        from heatmap_tpu.tilefs import DiskTileCache

        disk_cache = DiskTileCache(args.disk_cache,
                                   max_bytes=args.disk_cache_bytes)
    if getattr(args, "prewarm_events", None):
        from heatmap_tpu.tilefs import PrewarmConfig

        prewarm = PrewarmConfig(events=tuple(args.prewarm_events),
                                top_k=args.prewarm_top_k,
                                budget_s=args.prewarm_budget_s,
                                budget_bytes=args.prewarm_bytes)
    app = ServeApp(store, cache,
                   render_timeout_s=getattr(args, "render_timeout", None),
                   synopsis_default=getattr(args, "synopsis_default", False),
                   degrade=controller, disk_cache=disk_cache,
                   prewarm=prewarm)
    # Incident bundles capture the same state /healthz serves, plus the
    # mount fingerprint (no-ops without --incident-dir).
    from heatmap_tpu.obs import incident as incident_mod

    incident_mod.add_state_provider("healthz", app._health)
    incident_mod.add_state_provider("config", lambda: {
        "store": args.store, "layers": app.layer_names(),
        "cache_bytes": cache.max_bytes, "ttl_s": cache.ttl_s})
    stop_stream = None
    if args.follow_stream:
        stop_stream = _follow_stream(args, app)
    server = make_server(app, host=args.host, port=args.port)
    host, port = server.server_address[:2]
    # Warm before announcing readiness on stderr: a supervisor that
    # waits for the banner sees a server whose popular tiles are hot.
    # Budgeted (--prewarm-budget-s), so a huge log can't stall startup.
    app.prewarm_now(source="startup")
    print(json.dumps({
        "serving": f"http://{host}:{port}",
        "store": args.store,
        "layers": app.layer_names(),
        "cache_bytes": cache.max_bytes,
        "ttl_s": cache.ttl_s,
    }), file=sys.stderr, flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if stop_stream is not None:
            stop_stream()
        server.server_close()
        _export_trace(args, collector)
        if ev_log is not None:
            obs.set_event_log(None)
            ev_log.close()
    return 0


def _serve_fleet(args, collector, ev_log) -> int:
    """``serve --fleet N``: supervisor + router on --host/--port.

    Each backend is a child serve process over the same store artifact
    (its own LRU); the router fronts them with the rendezvous ring,
    breakers, hedging, and admission control (docs/serving.md)."""
    from heatmap_tpu import obs
    from heatmap_tpu.serve import make_server
    from heatmap_tpu.serve import degrade as degrade_mod
    from heatmap_tpu.serve.fleet import FleetSupervisor

    degrade_opts = None
    if getattr(args, "degrade", False):
        degrade_opts = {"dwell_s": getattr(args, "degrade_dwell", 10.0),
                        "hold_s": getattr(args, "degrade_hold", 30.0),
                        "ladder_spec": getattr(args, "degrade_ladder", "")}
        try:
            # Fail fast in the supervisor, not in every backend child.
            degrade_mod.parse_ladder_spec(degrade_opts["ladder_spec"])
        except ValueError as e:
            raise SystemExit(f"--degrade-ladder: {e}") from e
    supervisor = FleetSupervisor(
        args.store, args.fleet,
        host=args.host, cache_bytes=args.cache_bytes,
        backend_max_inflight=args.max_inflight,
        render_timeout_s=getattr(args, "render_timeout", None),
        chaos=getattr(args, "chaos", None),
        max_inflight=args.max_inflight or 32,
        queue_deadline_s=args.queue_deadline,
        hedge_quantile=args.hedge_quantile,
        probe_interval_s=args.probe_interval,
        degrade_opts=degrade_opts,
        slo_specs=list(getattr(args, "slo", None) or []),
        telemetry_opts=(
            {"interval": args.telemetry_sample_interval,
             "watches": list(getattr(args, "watch", None) or [])}
            if getattr(args, "telemetry_sample_interval", 0.0) else None),
        disk_cache_opts=({"root": args.disk_cache,
                          "max_bytes": args.disk_cache_bytes}
                         if getattr(args, "disk_cache", None) else None),
        prewarm_opts=({"events": list(args.prewarm_events),
                       "top_k": args.prewarm_top_k,
                       "budget_s": args.prewarm_budget_s,
                       "budget_bytes": args.prewarm_bytes}
                      if getattr(args, "prewarm_events", None) else None))
    from heatmap_tpu.obs import incident as incident_mod

    # Lazy: supervisor.router is None until supervisor.start() below.
    incident_mod.add_state_provider(
        "healthz",
        lambda: supervisor.router._health() if supervisor.router else {})
    incident_mod.add_state_provider("config", lambda: {
        "store": args.store, "fleet": args.fleet,
        "backends": {bid: c.address for bid, c
                     in supervisor.router.backends.items()}})
    supervisor.start()
    server = make_server(supervisor.router, host=args.host, port=args.port)
    host, port = server.server_address[:2]
    print(json.dumps({
        "serving": f"http://{host}:{port}",
        "store": args.store,
        "fleet": {bid: client.address for bid, client
                  in supervisor.router.backends.items()},
    }), file=sys.stderr, flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        supervisor.stop()
        _export_trace(args, collector)
        if ev_log is not None:
            obs.set_event_log(None)
            ev_log.close()
    return 0


def cmd_render(args) -> int:
    """Stored heatmaps -> z/x/y PNG tile tree.

    Closes the loop the reference left to an external web app (its
    blobs went to Cassandra for some other service to draw, reference
    heatmap.py:149-150): reads a columnar levels directory
    (``arrays:DIR`` / ``arrays-parquet:DIR``) or a blob JSONL
    (``jsonl:PATH``), selects one (user, timespan, zoom) slice, and
    renders PNG tiles from the stored counts — no re-aggregation.
    """
    import numpy as np

    from heatmap_tpu.io import PNGTileSink
    from heatmap_tpu.io.sinks import JSONLBlobSink, LevelArraysSink

    kind, _, rest = args.input.partition(":")
    if kind in ("arrays", "arrays-parquet"):
        levels = LevelArraysSink.load(rest)
        if not levels:
            raise SystemExit(f"no level files under {rest!r}")
        zoom = args.zoom if args.zoom is not None else max(levels)
        if zoom not in levels:
            raise SystemExit(
                f"zoom {zoom} not stored; available: {sorted(levels)}"
            )
        lvl = levels[zoom]
        keep = ((lvl["user"] == args.user)
                & (lvl["timespan"] == args.timespan))
        rows = lvl["row"][keep].astype(np.int64)
        cols = lvl["col"][keep].astype(np.int64)
        vals = lvl["value"][keep]
    elif kind == "jsonl" or args.input.endswith((".jsonl", ".ndjson")):
        from heatmap_tpu.tilemath.keys import parse_tile_id

        path = rest if kind == "jsonl" else args.input
        blobs = JSONLBlobSink.load(path)
        # One pass: collect every matching (z, r, c, v); pick/filter
        # the zoom afterwards. Malformed ids drop, mirroring the
        # reference parser (tilemath.keys.parse_tile_id).
        entries = []
        for blob_id, heat in blobs.items():
            user, ts, _coarse = blob_id.split("|", 2)
            if user != args.user or ts != args.timespan:
                continue
            for tile_id, v in heat.items():
                parsed = parse_tile_id(tile_id)
                if parsed is not None:
                    entries.append((*parsed, float(v)))
        zooms_seen = {e[0] for e in entries}
        zoom = args.zoom if args.zoom is not None else (
            max(zooms_seen) if zooms_seen else None
        )
        if zoom is None or zoom not in zooms_seen:
            raise SystemExit(
                f"zoom {zoom} not stored for "
                f"{args.user!r}/{args.timespan!r}; "
                f"available: {sorted(zooms_seen)}"
            )
        sel = [e for e in entries if e[0] == zoom]
        rows = np.asarray([e[1] for e in sel], np.int64)
        cols = np.asarray([e[2] for e in sel], np.int64)
        vals = np.asarray([e[3] for e in sel], np.float64)
    else:
        raise SystemExit(
            f"render input must be arrays:DIR, arrays-parquet:DIR or "
            f"jsonl:PATH, got {args.input!r}"
        )

    if len(rows) == 0:
        print(json.dumps({"tiles": 0, "output": args.output,
                          "user": args.user, "timespan": args.timespan}))
        return 0
    pixel_delta = min(args.pixel_delta, zoom)
    px = 1 << pixel_delta
    # Rasterize PER OCCUPIED OUTPUT TILE, not over one bounding box: a
    # spread dataset (two cities in the 'all' slice) would make the
    # dense bounding raster at detail zoom gigabytes; per-tile blocks
    # bound memory at px*px regardless of extent. One shared vmax so
    # the colormap is consistent across tiles.
    from heatmap_tpu.ops import Window

    t0 = time.perf_counter()
    tile_key = (rows // px) * (1 << 40) + (cols // px)
    order = np.argsort(tile_key, kind="stable")
    sorted_keys = tile_key[order]
    starts = np.flatnonzero(
        np.concatenate([[True], sorted_keys[1:] != sorted_keys[:-1]])
    )
    bounds = np.append(starts, len(sorted_keys))
    sink = PNGTileSink(args.output, pixel_delta=pixel_delta)
    vmax = float(vals.max())
    n = 0
    for k, s in enumerate(starts):
        sel = order[s:bounds[k + 1]]
        ty = int(rows[sel[0]]) // px
        tx = int(cols[sel[0]]) // px
        block = np.zeros(px * px, np.float64)
        np.add.at(block, (rows[sel] - ty * px) * px + (cols[sel] - tx * px),
                  vals[sel])
        window = Window(zoom=zoom, row0=ty * px, col0=tx * px,
                        height=px, width=px)
        n += sink.write_window(block.reshape(px, px), window, vmax=vmax)
    print(json.dumps({
        "tiles": n,
        "tile_zoom": zoom - pixel_delta,
        "zoom": zoom,
        "aggregates": int(len(rows)),
        "seconds": round(time.perf_counter() - t0, 3),
        "output": args.output,
    }))
    return 0


def cmd_convert(args) -> int:
    from heatmap_tpu.io.hmpb import convert_to_hmpb

    stats = convert_to_hmpb(args.input, args.output,
                            batch_size=args.batch_size,
                            shard_rows=args.shard_rows)
    print(json.dumps(stats))
    return 0


def cmd_merge(args) -> int:
    """Merge per-host egress shards into one artifact (no devices)."""
    import os

    from heatmap_tpu.io.merge import merge_blob_files, merge_level_dirs
    from heatmap_tpu.io.sinks import LevelArraysSink, open_sink

    dirs = [os.path.isdir(p) for p in args.inputs]
    columnar_out = args.output.startswith("arrays:")
    if all(dirs):
        if not columnar_out:
            # Writing level arrays through a blob-spec path would
            # produce a directory of .npz files under a name the
            # operator believes is a JSONL file.
            raise SystemExit(
                "level-array inputs merge into a columnar sink; pass "
                "--output arrays:DIR (got "
                f"{args.output!r})"
            )
        levels = merge_level_dirs(args.inputs)
        rows = LevelArraysSink(
            args.output[len("arrays:"):]
        ).write_levels(levels)
        print(json.dumps({"mode": "levels", "inputs": len(args.inputs),
                          "levels": len(levels), "rows": rows,
                          "output": args.output}))
        return 0
    if any(dirs):
        raise SystemExit(
            "merge inputs must be all JSONL blob files or all "
            "level-array directories, not a mix"
        )
    if columnar_out:
        raise SystemExit(
            "blob inputs merge into a blob sink (jsonl:/dir:/memory:); "
            f"arrays: is columnar-only (got {args.output!r})"
        )
    blobs = merge_blob_files(args.inputs)
    with open_sink(args.output) as sink:
        sink.write((k, json.dumps(v)) for k, v in blobs.items())
    print(json.dumps({"mode": "blobs", "inputs": len(args.inputs),
                      "blobs": len(blobs), "output": args.output}))
    return 0


def _add_temporal_flags(p):
    g = p.add_argument_group(
        "temporal buckets",
        "pin the epoch-bucketed partial-pyramid config "
        "(docs/temporal.md). Byte-affecting for temporal folds, so it "
        "follows the config-fingerprint discipline: the first writer "
        "sets it, later runs must match. Compactions then fold history "
        "into buckets/ and serve answers ?as_of=/?window=/?decay= "
        "tiles and op=topk_growth queries.")
    g.add_argument("--bucket-width", type=float, default=None,
                   metavar="UNITS",
                   help="tier-0 bucket width in watermark units "
                   "(setting any --bucket-* flag enables the temporal "
                   "plane; default width 3600)")
    g.add_argument("--bucket-fanout", type=int, default=None,
                   help="geometric ladder fanout: tier-j buckets are "
                   "width * fanout**j wide (default 4)")
    g.add_argument("--bucket-keep", type=int, default=None,
                   help="newest intervals kept per tier before history "
                   "coarsens into the next tier (default 8)")
    g.add_argument("--bucket-tiers", type=int, default=None,
                   help="ladder height; the top tier is unbounded "
                   "(default 4)")
    g.add_argument("--bucket-unit-s", type=float, default=None,
                   metavar="S",
                   help="seconds per watermark unit — scales the named "
                   "?window= values (1h/1d/1w); ms timestamps use "
                   "0.001 (default 1)")


def _ensure_temporal(args, root: str):
    """Pin the temporal config when any --bucket-* flag was passed;
    returns the active config (None = temporal plane not enabled)."""
    overrides = {"width": args.bucket_width, "fanout": args.bucket_fanout,
                 "keep": args.bucket_keep, "tiers": args.bucket_tiers,
                 "unit_s": args.bucket_unit_s}
    if all(v is None for v in overrides.values()):
        return None
    from heatmap_tpu.temporal import ensure_config

    os.makedirs(root, exist_ok=True)
    try:
        return ensure_config(root, **overrides)
    except ValueError as e:
        raise SystemExit(str(e)) from e


def _add_update_flags(p):
    p.add_argument("--journal", required=True, metavar="ROOT",
                   help="delta store root (journal/ + base + delta "
                   "artifacts; created on first use — "
                   "docs/incremental.md)")
    p.add_argument("--input", default=None,
                   help="source spec of NEW points to apply as one "
                   "journaled delta batch")
    p.add_argument("--retractions", default=None,
                   help="source spec of points to RETRACT (a signed "
                   "delta batch: their counts are subtracted)")
    p.add_argument("--base", default=None, type=_sink_spec,
                   metavar="arrays:DIR",
                   help="adopt an existing columnar artifact as the "
                   "store's initial base pyramid (copied in; only "
                   "valid once)")
    p.add_argument("--compact-after", type=int, default=None, metavar="N",
                   help="fold the delta stack into a new base when "
                   "more than N live deltas remain after this update "
                   "(0 = compact whenever any delta is live)")
    p.add_argument("--retention", type=int, default=2,
                   help="journal entries kept after compaction as the "
                   "idempotency window (size to the upstream's "
                   "redelivery horizon)")
    p.add_argument("--detail-zoom", type=int, default=21)
    p.add_argument("--min-detail-zoom", type=int, default=5)
    p.add_argument("--result-delta", type=int, default=5)
    p.add_argument("--timespans", default="alltime")
    p.add_argument("--batch-size", type=int, default=1 << 20)
    p.add_argument("--weighted", action="store_true",
                   help="sum the source's per-point 'value' column "
                   "instead of counting points")
    p.add_argument("--cascade-backend", default="auto",
                   choices=("auto", "scatter", "partitioned"))
    p.add_argument("--data-parallel", choices=("auto", "on", "off"),
                   default="auto")
    p.add_argument("--dispatch", choices=("auto", "gspmd", "shard_map"),
                   default="auto",
                   help="data-parallel cascade dispatch (docs/gspmd.md); "
                   "auto picks the one-program gspmd path wherever it "
                   "exists")
    p.add_argument("--metrics-dir", default=None, metavar="DIR",
                   help="enable the metrics registry and write "
                   "DIR/metrics.prom at command end")
    p.add_argument("--events", default=None, metavar="PATH",
                   help="append structured events to PATH (delta_applied, "
                   "compaction_start/end — docs/observability.md)")
    p.add_argument("--report", nargs="?", const="run_report.json",
                   default=None, metavar="PATH",
                   help="fold tracer + metrics + events into a run "
                   "report at PATH and print the span table to stderr")
    _add_temporal_flags(p)
    _add_trace_flags(p)


def cmd_update(args) -> int:
    """Incremental update: journaled delta applies + optional
    compaction against a delta store (heatmap_tpu.delta). The applied
    batches run the full cascade (auto routing included) over just the
    new points; the serving tier mounts the same root as ``serve
    --store delta:ROOT``."""
    from heatmap_tpu.pipeline.timespan import VALID_TYPES

    requested = tuple(t.strip() for t in args.timespans.split(",")
                      if t.strip())
    bad = [t for t in requested if t not in VALID_TYPES]
    if bad:
        raise SystemExit(
            f"--timespans: unknown type(s) {bad}; valid: "
            f"{', '.join(VALID_TYPES)}"
        )
    if not (args.input or args.retractions or args.base
            or args.compact_after is not None):
        raise SystemExit("nothing to do: pass --input and/or "
                         "--retractions, --base, or --compact-after")
    base_dir = None
    if args.base:
        # _sink_spec already validated the kind list; the store adopts
        # columnar artifacts only (that is the mergeable level format).
        if not args.base.startswith("arrays:"):
            raise SystemExit("--base must be a columnar arrays:DIR "
                             f"artifact, got {args.base!r}")
        base_dir = args.base[len("arrays:"):]
        if not os.path.isdir(base_dir):
            raise SystemExit(f"--base: {base_dir!r} is not a directory")
    config = None
    if args.input or args.retractions:
        _init_backend(args)
        from heatmap_tpu.pipeline import BatchJobConfig

        try:
            config = BatchJobConfig(
                detail_zoom=args.detail_zoom,
                min_detail_zoom=args.min_detail_zoom,
                result_delta=args.result_delta,
                timespans=requested,
                weighted=args.weighted,
                cascade_backend=args.cascade_backend,
                data_parallel={"auto": None, "on": True, "off": False}[
                    args.data_parallel],
                dispatch=args.dispatch,
            )
        except ValueError as e:
            raise SystemExit(str(e)) from e
    from heatmap_tpu import delta as delta_mod

    # Same opt-in telemetry contract as cmd_run: with every flag off
    # the update path emits/records nothing.
    telemetry = bool(args.metrics_dir or args.events
                     or args.report is not None)
    ev_log = None
    if telemetry:
        from heatmap_tpu import obs

        obs.enable_metrics(True)
        if args.events:
            ev_log = obs.EventLog(args.events)
            obs.set_event_log(ev_log)
            manifest = {}
            if config is not None:
                import dataclasses as _dc

                manifest = {k: (list(v) if isinstance(v, tuple) else v)
                            for k, v in _dc.asdict(config).items()}
            obs.emit("run_start", config=manifest, backend=args.backend,
                     devices=obs.device_topology(), argv=sys.argv[1:])
    from heatmap_tpu.obs import tracing as tracing_mod

    collector = _setup_tracing(args)
    from heatmap_tpu.obs import incident as incident_mod

    incident_mod.add_state_provider("delta", lambda: {
        "journal": args.journal,
        "live_deltas": len(delta_mod.live_entries(args.journal))})
    root_span = tracing_mod.begin_span("update")
    t0 = time.perf_counter()
    job_error = None
    summary = {"journal": args.journal}
    try:
        if base_dir is not None:
            delta_mod.init_store(args.journal, base_dir)
            summary["base_adopted"] = args.base
        tcfg = _ensure_temporal(args, args.journal)
        if tcfg is not None:
            summary["temporal"] = tcfg
        applied = []
        if args.input or args.retractions:
            from heatmap_tpu.io import open_source

            jobs = [(args.input, 1)] if args.input else []
            if args.retractions:
                jobs.append((args.retractions, -1))
            for spec, sign in jobs:
                res = delta_mod.apply_batch(
                    args.journal,
                    open_source(spec, read_value=args.weighted),
                    config, sign=sign, batch_size=args.batch_size)
                applied.append({
                    "input": spec, "epoch": res.epoch,
                    "points": res.points, "sign": res.sign,
                    "duplicate": res.duplicate, "rows": res.rows,
                    "affected_keys": len(res.affected_keys),
                })
        if applied:
            summary["applied"] = applied
        live = len(delta_mod.live_entries(args.journal))
        if args.compact_after is not None and live > args.compact_after:
            comp = delta_mod.compact(args.journal,
                                     retention=args.retention)
            summary["compaction"] = {
                k: comp.get(k) for k in ("status", "base",
                                         "applied_through", "rows",
                                         "pruned_entries")}
            live = len(delta_mod.live_entries(args.journal))
        summary["live_deltas"] = live
    except ValueError as e:
        # Config mismatch / double --base: operator errors, one line.
        _fail_telemetry(root_span, e)
        if not telemetry:
            tracing_mod.end_span(root_span)
            _export_trace(args, collector)
            raise SystemExit(str(e)) from e
        job_error = e
    except BaseException as e:  # noqa: BLE001 — run_end must record it
        _fail_telemetry(root_span, e)
        if not telemetry:
            tracing_mod.end_span(root_span)
            _export_trace(args, collector)
            raise
        job_error = e
    dt = time.perf_counter() - t0
    tracing_mod.end_span(root_span)
    if telemetry:
        from heatmap_tpu import obs
        from heatmap_tpu.utils.trace import get_tracer

        if ev_log is not None:
            end = {"status": "error" if job_error is not None else "ok",
                   "seconds": round(dt, 3)}
            if job_error is not None:
                end["error"] = repr(job_error)
            else:
                end["rows"] = int(sum(a["rows"] for a in
                                      summary.get("applied", [])))
            obs.emit("run_end", **end)
            obs.set_event_log(None)
            ev_log.close()
        if args.metrics_dir:
            obs.get_registry().write_prometheus(
                os.path.join(args.metrics_dir, "metrics.prom"))
        if args.report is not None:
            report = obs.build_run_report(
                tracer=get_tracer(), registry=obs.get_registry(),
                events_path=args.events)
            obs.write_run_report(args.report, report)
            print(obs.format_run_report(report), file=sys.stderr)
        if job_error is not None:
            _export_trace(args, collector)
            if isinstance(job_error, ValueError):
                raise SystemExit(str(job_error)) from job_error
            raise job_error
    _export_trace(args, collector)
    summary["seconds"] = round(dt, 3)
    print(json.dumps(summary))
    return 0


def cmd_retract(args) -> int:
    """Predicate retraction (delta/retract.py): scan the journal's
    point payloads for rows matching every --where clause, net them as
    a signed multiset, and apply exact sign=-1 counter-batches — one
    per (temporal bucket, column signature) group, so the all-time
    store AND every temporal fold converge to a clean recompute over
    the surviving points."""
    _init_backend(args)
    from heatmap_tpu.delta import retract as retract_mod

    pairs = list(args.where or [])
    if args.layer:
        pairs.append(f"user={args.layer}")
    try:
        where = retract_mod.parse_where(pairs)
    except ValueError as e:
        raise SystemExit(str(e)) from e
    ev_log = None
    if args.events:
        from heatmap_tpu import obs

        ev_log = obs.EventLog(args.events)
        obs.set_event_log(ev_log)
    try:
        summary = retract_mod.retract_predicate(
            args.journal, where, batch_size=args.batch_size)
    except ValueError as e:
        raise SystemExit(str(e)) from e
    finally:
        if ev_log is not None:
            from heatmap_tpu import obs

            obs.set_event_log(None)
            ev_log.close()
    out = {k: v for k, v in summary.items() if k != "results"}
    out["journal"] = args.journal
    out["where"] = {k: str(v) for k, v in sorted(where.items())}
    out["seconds"] = round(out["seconds"], 3)
    print(json.dumps(out))
    return 0


def _add_retract_flags(p):
    p.add_argument("--journal", required=True, metavar="ROOT",
                   help="delta store root whose journal is scanned")
    p.add_argument("--where", action="append", default=[],
                   metavar="COL=VALUE",
                   help="equality clause on a point column (repeatable; "
                   "clauses AND). Columns: user/user_id, source, "
                   "timestamp, latitude, longitude, value")
    p.add_argument("--layer", default=None, metavar="USER",
                   help="shorthand for --where user=USER (the serve "
                   "tier's layer name)")
    p.add_argument("--batch-size", type=int, default=1 << 20)
    p.add_argument("--events", default=None, metavar="PATH",
                   help="append structured events to PATH "
                   "(retraction_applied, delta_applied)")


def _add_ingest_flags(p):
    p.add_argument("--journal", required=True, metavar="ROOT",
                   help="delta store root the loop journals into "
                   "(created on first use; serve mounts it as "
                   "delta:ROOT — docs/ingest.md)")
    p.add_argument("--input", required=True,
                   help="source spec consumed as micro-batches "
                   "(each one journaled as its own signed epoch)")
    p.add_argument("--retract", action="store_true",
                   help="retract every batch instead of inserting "
                   "(sign=-1 epochs: counts are subtracted)")
    p.add_argument("--micro-batch", type=int, default=1 << 14,
                   help="points per tick (the journal/apply/publish "
                   "granularity)")
    p.add_argument("--queue-depth", type=int, default=4,
                   help="bounded-queue depth between the source reader "
                   "and the apply loop; a full queue blocks the "
                   "reader (back-pressure). 0 = synchronous, no "
                   "reader thread")
    p.add_argument("--max-ticks", type=int, default=None,
                   help="stop after N ticks (default: drain the source)")
    p.add_argument("--compact-every", type=int, default=16, metavar="N",
                   help="fold the delta stack into a new base whenever "
                   "N live deltas accumulate (0 = never)")
    p.add_argument("--compact-max-age", type=float, default=0.0,
                   metavar="S",
                   help="also compact when the oldest live delta is "
                   "older than S seconds (0 = never)")
    p.add_argument("--retention", type=int, default=2,
                   help="journal entries kept after compaction as the "
                   "idempotency window")
    p.add_argument("--pad-bucketing", default="pow2",
                   choices=("pow2", "geometric", "exact"),
                   help="bucketed-padding compile cache for the "
                   "cascade (pipeline/bucketing.py): pow2/geometric "
                   "reuse one compilation per size bucket; exact "
                   "compiles per distinct batch size")
    p.add_argument("--pad-bucket-min", type=int, default=1 << 12,
                   help="bucket floor: batches below this many "
                   "emissions share one compilation")
    p.add_argument("--serve-port", type=int, default=None, metavar="PORT",
                   help="serve the store over HTTP from this process "
                   "while ingesting (0 = ephemeral port; bound "
                   "address printed to stderr); each tick publishes "
                   "via targeted invalidation")
    p.add_argument("--detail-zoom", type=int, default=21)
    p.add_argument("--min-detail-zoom", type=int, default=5)
    p.add_argument("--result-delta", type=int, default=5)
    p.add_argument("--timespans", default="alltime")
    p.add_argument("--weighted", action="store_true",
                   help="sum the source's per-point 'value' column "
                   "instead of counting points")
    p.add_argument("--cascade-backend", default="auto",
                   choices=("auto", "scatter", "partitioned"))
    p.add_argument("--data-parallel", choices=("auto", "on", "off"),
                   default="auto")
    p.add_argument("--dispatch", choices=("auto", "gspmd", "shard_map"),
                   default="auto",
                   help="data-parallel cascade dispatch (docs/gspmd.md); "
                   "auto picks the one-program gspmd path wherever it "
                   "exists")
    p.add_argument("--metrics-dir", default=None, metavar="DIR",
                   help="enable the metrics registry and write "
                   "DIR/metrics.prom at command end")
    p.add_argument("--events", default=None, metavar="PATH",
                   help="append structured events to PATH (ingest_tick, "
                   "delta_applied, compaction_start/end — "
                   "docs/observability.md)")
    p.add_argument("--report", nargs="?", const="run_report.json",
                   default=None, metavar="PATH",
                   help="fold tracer + metrics + events into a run "
                   "report at PATH and print the span table to stderr")
    _add_temporal_flags(p)
    _add_trace_flags(p)


def cmd_ingest(args) -> int:
    """Continuous ingest: drain a source through the bounded-queue
    loop (heatmap_tpu.ingest) — every micro-batch journals as a signed
    epoch, applies through the bucketed cascade, and (with
    --serve-port) publishes to an in-process tile server via targeted
    invalidation. A ``staleness`` SLO over tick recency rides the
    shared --slo flag, e.g. ``--slo fresh:staleness:max_age_s=30``."""
    from heatmap_tpu.pipeline.timespan import VALID_TYPES

    requested = tuple(t.strip() for t in args.timespans.split(",")
                      if t.strip())
    bad = [t for t in requested if t not in VALID_TYPES]
    if bad:
        raise SystemExit(
            f"--timespans: unknown type(s) {bad}; valid: "
            f"{', '.join(VALID_TYPES)}"
        )
    _init_backend(args)
    from heatmap_tpu import delta as delta_mod
    from heatmap_tpu import ingest as ingest_mod
    from heatmap_tpu.io import open_source
    from heatmap_tpu.pipeline import BatchJobConfig, bucketing

    try:
        config = BatchJobConfig(
            detail_zoom=args.detail_zoom,
            min_detail_zoom=args.min_detail_zoom,
            result_delta=args.result_delta,
            timespans=requested,
            weighted=args.weighted,
            cascade_backend=args.cascade_backend,
            data_parallel={"auto": None, "on": True, "off": False}[
                args.data_parallel],
            dispatch=args.dispatch,
            pad_bucketing=args.pad_bucketing,
            pad_bucket_min=args.pad_bucket_min,
        )
        ing = ingest_mod.IngestConfig(
            micro_batch=args.micro_batch,
            queue_depth=args.queue_depth or None,
            sign=-1 if args.retract else 1,
            compact_every=args.compact_every,
            compact_max_age_s=args.compact_max_age,
            retention=args.retention,
            max_ticks=args.max_ticks,
        )
    except ValueError as e:
        raise SystemExit(str(e)) from e

    telemetry = bool(args.metrics_dir or args.events
                     or args.report is not None)
    ev_log = None
    if telemetry:
        from heatmap_tpu import obs

        obs.enable_metrics(True)
        if args.events:
            ev_log = obs.EventLog(args.events)
            obs.set_event_log(ev_log)
            import dataclasses as _dc

            manifest = {k: (list(v) if isinstance(v, tuple) else v)
                        for k, v in _dc.asdict(config).items()}
            obs.emit("run_start", config=manifest, backend=args.backend,
                     devices=obs.device_topology(), argv=sys.argv[1:])
    from heatmap_tpu.obs import tracing as tracing_mod

    collector = _setup_tracing(args)
    from heatmap_tpu.obs import incident as incident_mod

    incident_mod.add_state_provider("delta", lambda: {
        "journal": args.journal,
        "live_deltas": len(delta_mod.live_entries(args.journal))})
    root_span = tracing_mod.begin_span("ingest")
    t0 = time.perf_counter()
    job_error = None
    server = None
    summary = {"journal": args.journal}
    try:
        delta_mod.init_store(args.journal)
        tcfg = _ensure_temporal(args, args.journal)
        if tcfg is not None:
            summary["temporal"] = tcfg
        store = cache = None
        if args.serve_port is not None:
            from heatmap_tpu.serve import (ServeApp, TileCache, TileStore,
                                           serve_in_thread)

            store = TileStore(f"delta:{args.journal}")
            cache = TileCache()
            server, base_url = serve_in_thread(
                ServeApp(store, cache), port=args.serve_port)
            summary["serving"] = base_url
            print(f"serving {base_url}/tiles/... while ingesting",
                  file=sys.stderr)
        stats = ingest_mod.run_ingest(
            args.journal, open_source(args.input, read_value=args.weighted),
            config, ingest=ing, store=store, cache=cache)
        summary.update({
            "ticks": stats.ticks, "points": stats.points,
            "epochs": len(stats.epochs), "duplicates": stats.duplicates,
            "watermark": stats.watermark,
            "max_queue_depth": stats.max_queue_depth,
            "compactions": stats.compactions,
            "keys_invalidated": stats.keys_invalidated,
            "live_deltas": len(delta_mod.live_entries(args.journal)),
            "compile_cache": bucketing.cache_stats(),
        })
    except ValueError as e:
        _fail_telemetry(root_span, e)
        if not telemetry:
            tracing_mod.end_span(root_span)
            _export_trace(args, collector)
            raise SystemExit(str(e)) from e
        job_error = e
    except BaseException as e:  # noqa: BLE001 — run_end must record it
        _fail_telemetry(root_span, e)
        if not telemetry:
            tracing_mod.end_span(root_span)
            _export_trace(args, collector)
            raise
        job_error = e
    finally:
        if server is not None:
            server.shutdown()
    dt = time.perf_counter() - t0
    tracing_mod.end_span(root_span)
    if telemetry:
        from heatmap_tpu import obs
        from heatmap_tpu.utils.trace import get_tracer

        if ev_log is not None:
            end = {"status": "error" if job_error is not None else "ok",
                   "seconds": round(dt, 3)}
            if job_error is not None:
                end["error"] = repr(job_error)
            else:
                end["rows"] = int(summary.get("points", 0))
            obs.emit("run_end", **end)
            obs.set_event_log(None)
            ev_log.close()
        if args.metrics_dir:
            obs.get_registry().write_prometheus(
                os.path.join(args.metrics_dir, "metrics.prom"))
        if args.report is not None:
            report = obs.build_run_report(
                tracer=get_tracer(), registry=obs.get_registry(),
                events_path=args.events)
            obs.write_run_report(args.report, report)
            print(obs.format_run_report(report), file=sys.stderr)
        if job_error is not None:
            _export_trace(args, collector)
            if isinstance(job_error, ValueError):
                raise SystemExit(str(job_error)) from job_error
            raise job_error
    _export_trace(args, collector)
    summary["seconds"] = round(dt, 3)
    print(json.dumps(summary))
    return 0


def _add_writeplane_flags(p):
    p.add_argument("--root", required=True, metavar="ROOT",
                   help="write-plane root (created on first use; serve "
                   "mounts it as writeplane:ROOT — docs/write-plane.md)")
    p.add_argument("--input", default=None,
                   help="insert source spec, drained as micro-batches "
                   "routed by Morton range")
    p.add_argument("--retractions", default=None,
                   help="retraction source spec (sign=-1 batches, "
                   "applied after --input)")
    p.add_argument("--writers", type=int, default=2,
                   help="ingest pumps = initial Morton ranges "
                   "(rebalance can add more)")
    p.add_argument("--micro-batch", type=int, default=1 << 14,
                   help="points per routed batch (the ledger/dedup "
                   "granularity — replays must use the same batching)")
    p.add_argument("--queue-depth", type=int, default=4,
                   help="bounded per-range queue depth between the "
                   "router and each pump")
    p.add_argument("--publish-every", type=int, default=1, metavar="N",
                   help="flip a manifest epoch every N finished batches")
    p.add_argument("--compact-every", type=int, default=16, metavar="N",
                   help="fold a range whenever N live deltas accumulate "
                   "(0 = never)")
    p.add_argument("--retention", type=int, default=2,
                   help="per-range journal entries kept after "
                   "compaction (refused below the retention floor or "
                   "the in-flight queue depth)")
    p.add_argument("--retention-floor", type=int, default=2,
                   help="hard floor under --retention (docs/"
                   "write-plane.md)")
    p.add_argument("--ledger-keep", type=int, default=64,
                   help="full-batch ledger entries retained (the "
                   "cross-rebalance dedup window)")
    p.add_argument("--max-ticks", type=int, default=None,
                   help="stop after N micro-batches (default: drain)")
    p.add_argument("--rebalance", action="store_true",
                   help="run one skew-triggered hot-range re-split "
                   "after the drain (docs/write-plane.md runbook)")
    p.add_argument("--pad-bucketing", default="pow2",
                   choices=("pow2", "geometric", "exact"),
                   help="bucketed-padding compile cache for the "
                   "cascade (pipeline/bucketing.py); routed sub-batch "
                   "sizes vary every tick, so exact mode compiles per "
                   "distinct size")
    p.add_argument("--pad-bucket-min", type=int, default=1 << 12,
                   help="bucket floor: sub-batches below this many "
                   "emissions share one compilation")
    p.add_argument("--detail-zoom", type=int, default=21)
    p.add_argument("--min-detail-zoom", type=int, default=5)
    p.add_argument("--result-delta", type=int, default=5)
    p.add_argument("--timespans", default="alltime")
    p.add_argument("--weighted", action="store_true")
    p.add_argument("--cascade-backend", default="auto",
                   choices=("auto", "scatter", "partitioned"))
    p.add_argument("--data-parallel", choices=("auto", "on", "off"),
                   default="auto")
    p.add_argument("--dispatch", choices=("auto", "gspmd", "shard_map"),
                   default="auto")
    p.add_argument("--metrics-dir", default=None, metavar="DIR",
                   help="enable the metrics registry and write "
                   "DIR/metrics.prom at command end")
    p.add_argument("--events", default=None, metavar="PATH",
                   help="append structured events to PATH "
                   "(writeplane_append/publish/rebalance — "
                   "docs/observability.md)")
    p.add_argument("--report", nargs="?", const="run_report.json",
                   default=None, metavar="PATH")
    _add_trace_flags(p)


def cmd_writeplane(args) -> int:
    """Partitioned multi-writer ingest: batches route by Morton range
    to independent per-range delta stores (one pump each), unified for
    readers by an epoch-flipped manifest (heatmap_tpu.writeplane).
    Serve mounts the root as ``writeplane:ROOT``."""
    from heatmap_tpu.pipeline.timespan import VALID_TYPES

    requested = tuple(t.strip() for t in args.timespans.split(",")
                      if t.strip())
    bad = [t for t in requested if t not in VALID_TYPES]
    if bad:
        raise SystemExit(f"--timespans: unknown type(s) {bad}; valid: "
                         f"{', '.join(VALID_TYPES)}")
    _init_backend(args)
    import statistics

    from heatmap_tpu import writeplane as wp_mod
    from heatmap_tpu.io import open_source
    from heatmap_tpu.pipeline import BatchJobConfig

    try:
        config = BatchJobConfig(
            detail_zoom=args.detail_zoom,
            min_detail_zoom=args.min_detail_zoom,
            result_delta=args.result_delta,
            timespans=requested,
            weighted=args.weighted,
            cascade_backend=args.cascade_backend,
            data_parallel={"auto": None, "on": True, "off": False}[
                args.data_parallel],
            dispatch=args.dispatch,
            pad_bucketing=args.pad_bucketing,
            pad_bucket_min=args.pad_bucket_min,
        )
        plane_cfg = wp_mod.PlaneConfig(
            n_writers=args.writers,
            retention=args.retention,
            retention_floor=args.retention_floor,
            compact_every=args.compact_every,
            ledger_keep=args.ledger_keep,
        )
    except ValueError as e:
        raise SystemExit(str(e)) from e

    telemetry = bool(args.metrics_dir or args.events
                     or args.report is not None)
    ev_log = None
    if telemetry:
        from heatmap_tpu import obs

        obs.enable_metrics(True)
        if args.events:
            ev_log = obs.EventLog(args.events)
            obs.set_event_log(ev_log)
            import dataclasses as _dc

            manifest = {k: (list(v) if isinstance(v, tuple) else v)
                        for k, v in _dc.asdict(config).items()}
            obs.emit("run_start", config=manifest, backend=args.backend,
                     devices=obs.device_topology(), argv=sys.argv[1:])
    from heatmap_tpu.obs import tracing as tracing_mod

    collector = _setup_tracing(args)
    from heatmap_tpu.obs import incident as incident_mod

    incident_mod.add_state_provider("writeplane", lambda: {
        "root": args.root,
        "epoch": wp_mod.read_pointer(args.root)})
    root_span = tracing_mod.begin_span("writeplane")
    t0 = time.perf_counter()
    job_error = None
    summary = {"root": args.root}
    try:
        plane = wp_mod.WritePlane(args.root, config, plane_cfg)
        runs = []
        jobs = [(args.input, 1)] if args.input else []
        if args.retractions:
            jobs.append((args.retractions, -1))
        for spec, sign in jobs:
            stats = wp_mod.run_plane_ingest(
                plane, open_source(spec, read_value=args.weighted),
                micro_batch=args.micro_batch, sign=sign,
                queue_depth=args.queue_depth,
                publish_every=args.publish_every,
                max_ticks=args.max_ticks)
            runs.append({
                "input": spec, "sign": sign, "batches": stats.batches,
                "completed": stats.completed,
                "duplicates": stats.duplicates, "failed": stats.failed,
                "points": stats.points, "publishes": stats.publishes,
                "publish_errors": stats.publish_errors,
                "lag_p50_s": (round(statistics.median(stats.lags_s), 6)
                              if stats.lags_s else None),
            })
        if runs:
            summary["runs"] = runs
        if args.rebalance:
            rb = plane.rebalance()
            summary["rebalance"] = (
                None if rb is None else
                {k: rb[k] for k in ("range", "new_range", "split",
                                    "epoch")})
        summary["epoch"] = plane.publish()
        summary["ranges"] = plane.order
    except ValueError as e:
        _fail_telemetry(root_span, e)
        if not telemetry:
            tracing_mod.end_span(root_span)
            _export_trace(args, collector)
            raise SystemExit(str(e)) from e
        job_error = e
    except BaseException as e:  # noqa: BLE001 — run_end must record it
        _fail_telemetry(root_span, e)
        if not telemetry:
            tracing_mod.end_span(root_span)
            _export_trace(args, collector)
            raise
        job_error = e
    dt = time.perf_counter() - t0
    tracing_mod.end_span(root_span)
    if telemetry:
        from heatmap_tpu import obs
        from heatmap_tpu.utils.trace import get_tracer

        if ev_log is not None:
            end = {"status": "error" if job_error is not None else "ok",
                   "seconds": round(dt, 3)}
            if job_error is not None:
                end["error"] = repr(job_error)
            else:
                end["rows"] = int(sum(r["points"] for r in
                                      summary.get("runs", [])))
            obs.emit("run_end", **end)
            obs.set_event_log(None)
            ev_log.close()
        if args.metrics_dir:
            obs.get_registry().write_prometheus(
                os.path.join(args.metrics_dir, "metrics.prom"))
        if args.report is not None:
            report = obs.build_run_report(
                tracer=get_tracer(), registry=obs.get_registry(),
                events_path=args.events)
            obs.write_run_report(args.report, report)
            print(obs.format_run_report(report), file=sys.stderr)
        if job_error is not None:
            _export_trace(args, collector)
            if isinstance(job_error, ValueError):
                raise SystemExit(str(job_error)) from job_error
            raise job_error
    _export_trace(args, collector)
    summary["seconds"] = round(dt, 3)
    print(json.dumps(summary))
    return 0


def cmd_info(args) -> int:
    # info reports unreachability as structured JSON (below) rather
    # than the fail-fast SystemExit the job commands want; an explicit
    # positive --device-timeout acts as the probe timeout (both flags
    # name the same wait here — honoring it beats silently preferring
    # --probe-timeout). 0 keeps its documented "no fail-fast probe"
    # meaning: info's own discovery probe stays on --probe-timeout.
    if args.device_timeout:
        args.probe_timeout = args.device_timeout
    args.device_timeout = 0.0
    jax = _init_backend(args)
    from heatmap_tpu import native

    # Device discovery in a KILLABLE worker thread: on the accelerator
    # backend, jax.devices() blocks inside backend init when the relay
    # tunnel is down, and an `info` command must never hang a terminal
    # (discovered against a dead relay 2026-07-31 — bench.py probes for
    # exactly the same reason).
    import threading

    dev_info = {}

    def _probe():
        devs = jax.devices()
        dev_info.update(platform=devs[0].platform, n_devices=len(devs),
                        n_processes=jax.process_count())

    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(timeout=args.probe_timeout)
    if t.is_alive():
        dev_info = {"platform": "unreachable", "n_devices": 0,
                    "note": f"backend init exceeded {args.probe_timeout:.0f}s "
                            "(accelerator relay down?); rerun with "
                            "--backend cpu for host info"}
    print(
        json.dumps(
            {
                "backend": args.backend,
                **dev_info,
                "x64": bool(jax.config.jax_enable_x64),
                "native": native.available(),
                "version": __import__("heatmap_tpu").__version__,
            }
        )
    )
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="heatmap-tpu",
        description="TPU-native heatmap aggregation (reference parity: "
        "timfpark/heatmap batch job)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_run = sub.add_parser("run", help="batch job: points -> heatmap blobs")
    _add_backend_flags(p_run)
    _add_run_flags(p_run)
    p_run.set_defaults(fn=cmd_run)

    p_tiles = sub.add_parser("tiles", help="points -> z/x/y PNG tile tree")
    _add_backend_flags(p_tiles)
    p_tiles.add_argument("--input", required=True)
    p_tiles.add_argument("--output", default="tiles")
    p_tiles.add_argument("--zoom", type=int, default=16,
                         help="detail (pixel) zoom")
    p_tiles.add_argument("--pixel-delta", type=int, default=8,
                         help="tile zoom = zoom - pixel_delta; 8 -> 256px tiles")
    p_tiles.add_argument("--lat-min", type=float, default=45.0)
    p_tiles.add_argument("--lat-max", type=float, default=50.0)
    p_tiles.add_argument("--lon-min", type=float, default=-125.0)
    p_tiles.add_argument("--lon-max", type=float, default=-119.0)
    p_tiles.add_argument("--auto-bounds", action="store_true",
                         help="derive the window from the data's "
                         "bounding box (one extra pass over the "
                         "source) instead of the --lat/--lon flags")
    p_tiles.add_argument("--batch-size", type=int, default=1 << 20)
    p_tiles.add_argument("--splat", type=int, default=0, metavar="K",
                         help="smooth with a KxK Gaussian kernel before "
                         "rendering (e.g. 9; 0 = off)")
    p_tiles.add_argument("--sigma", type=float, default=None,
                         help="Gaussian sigma in cells (default K/4)")
    p_tiles.add_argument("--weighted", action="store_true",
                         help="sum the input's per-point 'value' column "
                         "instead of counting points (BASELINE config 3)")
    p_tiles.add_argument("--bin-backend", default="auto",
                         choices=("auto", "xla", "pallas", "partitioned"),
                         help="binning path (as in bench.py): auto routes "
                         "TPU windows to the measured-fastest kernel; xla "
                         "is the plain scatter")
    p_tiles.set_defaults(fn=cmd_tiles)

    p_stream = sub.add_parser(
        "stream",
        help="micro-batch streaming: decayed live raster -> PNG tiles "
        "(BASELINE.md config 4)",
    )
    _add_backend_flags(p_stream)
    p_stream.add_argument("--input", required=True,
                          help="source spec, consumed as micro-batches")
    p_stream.add_argument("--output", default=None,
                          help="PNG tile tree dir for the final snapshot "
                          "('' = none; default: live_tiles/ under "
                          "--live-dir)")
    p_stream.add_argument("--live-dir", default=None,
                          help="root for runtime tile artifacts (default: "
                          "--checkpoint-dir when given, else the system "
                          "tmp dir)")
    p_stream.add_argument("--batch-points", type=int, default=1 << 16,
                          help="points per micro-batch (one compiled step)")
    p_stream.add_argument("--bin-backend", default="auto",
                          choices=("auto", "xla", "pallas", "partitioned"),
                          help="binning backend for the update step "
                          "(StreamConfig.backend); pin per "
                          "tools/bench_stream.py measurements — CPU "
                          "rows in onchip_state/sweep.jsonl show xla "
                          "winning there; the on-chip default flip is "
                          "decision rule (d), PERF_NOTES.md")
    p_stream.add_argument("--interval", type=float, default=60.0,
                          help="stream seconds advanced per micro-batch")
    p_stream.add_argument("--half-life", type=float, default=3600.0,
                          help="decay half-life in stream seconds")
    p_stream.add_argument("--zoom", type=int, default=12)
    p_stream.add_argument("--pixel-delta", type=int, default=8)
    p_stream.add_argument("--lat-min", type=float, default=45.0)
    p_stream.add_argument("--lat-max", type=float, default=50.0)
    p_stream.add_argument("--lon-min", type=float, default=-125.0)
    p_stream.add_argument("--lon-max", type=float, default=-119.0)
    p_stream.add_argument("--auto-bounds", action="store_true",
                          help="derive the window from the data's "
                          "bounding box (file sources only: one extra "
                          "pass; resume keeps the same window for the "
                          "same file)")
    p_stream.add_argument("--checkpoint-dir", default=None)
    p_stream.add_argument("--checkpoint-every", type=int, default=16)
    p_stream.add_argument("--weighted", action="store_true",
                          help="sum the input's per-point 'value' column "
                          "into the decayed raster instead of counting")
    p_stream.set_defaults(fn=cmd_stream)

    p_serve = sub.add_parser(
        "serve",
        help="tile HTTP server over stored heatmaps: "
        "GET /tiles/{layer}/{z}/{x}/{y}.png|.json (docs/serving.md)",
    )
    _add_backend_flags(p_serve)  # used only by --follow-stream
    p_serve.add_argument("--store", required=True,
                         help="arrays:DIR (incl. multihost host*/ shard "
                         "dirs) | jsonl:PATH | dir:PATH — any batch "
                         "egress artifact")
    p_serve.add_argument("--host", default="127.0.0.1")
    p_serve.add_argument("--port", type=int, default=8000,
                         help="listen port (0 = ephemeral; the bound "
                         "address is printed to stderr)")
    p_serve.add_argument("--cache-bytes", type=int, default=256 << 20,
                         help="tile cache budget in bytes (LRU past it; "
                         "0 disables caching but keeps single-flight "
                         "render dedup)")
    p_serve.add_argument("--ttl", type=float, default=None,
                         help="tile cache TTL seconds (default: none for "
                         "static stores; live mode defaults to "
                         "interval/2 to bound decay drift)")
    p_serve.add_argument("--layers", default=None,
                         help="comma list of name=user|timespan layer "
                         "mounts (default: every slice in the artifact "
                         "plus 'default' -> all|alltime)")
    p_serve.add_argument("--synopsis-default", action="store_true",
                         help="serve coarse tiles from wavelet synopses "
                         "by default (docs/synopsis.md); per-request "
                         "?synopsis=0/1 always wins")
    p_serve.add_argument("--render-timeout", type=float, default=None,
                         metavar="S",
                         help="per-tile render deadline in seconds; a "
                         "render past it serves the last-good cached "
                         "bytes (stale-200) or a typed 503, never a "
                         "hung request (docs/robustness.md)")
    p_serve.add_argument("--fleet", type=int, default=None, metavar="N",
                         help="run N shared-nothing backend processes "
                         "behind a consistent-hash router on --port "
                         "(rendezvous ring, circuit breakers, hedged "
                         "reads, admission control; docs/serving.md). "
                         "Incompatible with --follow-stream")
    p_serve.add_argument("--max-inflight", type=int, default=None,
                         metavar="N",
                         help="admission bound: concurrent tile requests "
                         "per process (router: per backend); past it "
                         "requests shed with 503 + Retry-After. "
                         "Fleet default: 32")
    p_serve.add_argument("--queue-deadline", type=float, default=0.25,
                         metavar="S",
                         help="fleet router: how long a request may wait "
                         "for a backend slot before shedding")
    p_serve.add_argument("--hedge-quantile", type=float, default=0.95,
                         help="fleet router: hedge a request to the next "
                         "replica once it outlives this latency "
                         "quantile (first answer wins)")
    p_serve.add_argument("--probe-interval", type=float, default=1.0,
                         metavar="S",
                         help="fleet router: active health-probe period "
                         "(half-open probes re-admit recovered "
                         "backends)")
    p_serve.add_argument("--degrade", action="store_true",
                         help="arm the brownout controller: SLO burn "
                         "(--slo) steps a rung ladder that trades tile "
                         "fidelity for availability under overload "
                         "(docs/robustness.md). Off by default")
    p_serve.add_argument("--degrade-dwell", type=float, default=10.0,
                         metavar="S",
                         help="seconds the burn must stay above the up "
                         "threshold before the ladder steps up one rung")
    p_serve.add_argument("--degrade-hold", type=float, default=30.0,
                         metavar="S",
                         help="seconds the burn must stay below the down "
                         "threshold before the ladder steps back down")
    p_serve.add_argument("--degrade-ladder", default="", metavar="SPEC",
                         help="ladder tuning, comma list of k=v: "
                         "up=BURN,down=BURN,ttl=SCALE,shed=FRAC,max=RUNG "
                         "(default up=1.0,down=0.5,ttl=4,shed=0.5,max=3)")
    p_serve.add_argument("--disk-cache", default=None, metavar="DIR",
                         help="persist rendered tile bytes under DIR as "
                         "a second cache tier below the heap LRU "
                         "(docs/tilefs.md): survives restarts, torn "
                         "entries read as misses, keys carry the exact "
                         "invalidation epochs. Fleet mode gives each "
                         "backend DIR/<backend-id>")
    p_serve.add_argument("--disk-cache-bytes", type=int, default=1 << 30,
                         metavar="B",
                         help="disk cache size cap (mtime-LRU eviction)")
    p_serve.add_argument("--prewarm-events", action="append", default=None,
                         metavar="PATH",
                         help="replay the Zipf head of these http_request "
                         "event logs (--events from a prior run) into "
                         "the caches at startup and after /reload; "
                         "repeatable (docs/tilefs.md)")
    p_serve.add_argument("--prewarm-top-k", type=int, default=64,
                         metavar="K",
                         help="how many of the most popular tile paths "
                         "the prewarm replays (decayed frequency rank)")
    p_serve.add_argument("--prewarm-budget-s", type=float, default=10.0,
                         metavar="S",
                         help="wall-clock budget for one prewarm pass")
    p_serve.add_argument("--prewarm-bytes", type=int, default=64 << 20,
                         metavar="B",
                         help="rendered-byte budget for one prewarm pass")
    p_serve.add_argument("--events", default=None, metavar="PATH",
                         help="append http_request events to PATH (JSONL, "
                         "docs/observability.md)")
    _add_trace_flags(p_serve)
    p_serve.add_argument("--follow-stream", default=None, metavar="SPEC",
                         help="live mode: consume this source spec as "
                         "micro-batches into a decayed stream layer "
                         "(name via --live-layer); ticks invalidate "
                         "only the affected tile keys")
    p_serve.add_argument("--live-layer", default="live",
                         help="layer name the --follow-stream raster is "
                         "served under")
    p_serve.add_argument("--batch-points", type=int, default=1 << 16)
    p_serve.add_argument("--interval", type=float, default=60.0,
                         help="stream seconds advanced per micro-batch")
    p_serve.add_argument("--tick-seconds", type=float, default=1.0,
                         help="wall-clock pause between micro-batch "
                         "ticks (0 = consume as fast as possible)")
    p_serve.add_argument("--half-life", type=float, default=3600.0)
    p_serve.add_argument("--zoom", type=int, default=12,
                         help="live window detail zoom")
    p_serve.add_argument("--lat-min", type=float, default=45.0)
    p_serve.add_argument("--lat-max", type=float, default=50.0)
    p_serve.add_argument("--lon-min", type=float, default=-125.0)
    p_serve.add_argument("--lon-max", type=float, default=-119.0)
    p_serve.set_defaults(fn=cmd_serve)

    p_render = sub.add_parser(
        "render",
        help="stored heatmaps (arrays:DIR / jsonl:PATH) -> PNG tile tree",
    )
    p_render.add_argument("--input", required=True,
                          help="arrays:DIR, arrays-parquet:DIR or jsonl:PATH")
    p_render.add_argument("--output", default="rendered_tiles")
    p_render.add_argument("--user", default="all",
                          help="user slice to render (default 'all')")
    p_render.add_argument("--timespan", default="alltime")
    p_render.add_argument("--zoom", type=int, default=None,
                          help="stored detail zoom to render "
                          "(default: finest available)")
    p_render.add_argument("--pixel-delta", type=int, default=8)
    p_render.set_defaults(fn=cmd_render)

    p_conv = sub.add_parser(
        "convert",
        help="convert a source to the HMPB binary columnar point format "
        "(mmap ingest for --fast reruns)",
    )
    p_conv.add_argument("--input", required=True, help="any source spec")
    p_conv.add_argument("--output", required=True,
                        help="output .hmpb path (a directory of part "
                        "files with --shard-rows)")
    p_conv.add_argument("--batch-size", type=int, default=1 << 20)
    p_conv.add_argument("--shard-rows", type=int, default=None,
                        help="split the output into part-NNNNN.hmpb "
                        "files of at most this many rows (the "
                        "range-shardable multihost ingest layout)")
    p_conv.set_defaults(fn=cmd_convert)

    p_merge = sub.add_parser(
        "merge",
        help="merge egress shards (per-host jsonl blob files or "
             "level-array dirs) into one artifact; colliding blob ids "
             "sum, exactly like the cross-host merge",
    )
    p_merge.add_argument("--inputs", nargs="+", required=True,
                         help="JSONL blob files, or level-array dirs "
                         "(all one kind)")
    p_merge.add_argument("--output", required=True, type=_sink_spec,
                         help="blob sink spec (jsonl:/dir:/memory:) for "
                         "blob inputs; arrays:DIR for level-array "
                         "inputs")
    p_merge.set_defaults(fn=cmd_merge)

    p_update = sub.add_parser(
        "update",
        help="incremental update: journaled delta apply + compaction "
        "against a delta store (serve mounts it as delta:ROOT)")
    _add_backend_flags(p_update)
    _add_update_flags(p_update)
    p_update.set_defaults(fn=cmd_update)

    p_ingest = sub.add_parser(
        "ingest",
        help="continuous ingest: source -> bounded queue -> journaled "
        "epochs -> servable tiles, with the bucketed compile cache "
        "(docs/ingest.md)")
    _add_backend_flags(p_ingest)
    _add_ingest_flags(p_ingest)
    p_ingest.set_defaults(fn=cmd_ingest)

    p_retract = sub.add_parser(
        "retract",
        help="predicate retraction against a delta store: journal scan "
        "-> exact signed counter-batches, byte-identical to a "
        "recompute over the surviving points (docs/temporal.md)")
    _add_backend_flags(p_retract)
    _add_retract_flags(p_retract)
    p_retract.set_defaults(fn=cmd_retract)

    p_wp = sub.add_parser(
        "writeplane",
        help="partitioned multi-writer ingest: Morton-range-sharded "
        "journals + epoch-unified manifest (serve mounts the root as "
        "writeplane:ROOT — docs/write-plane.md)")
    _add_backend_flags(p_wp)
    _add_writeplane_flags(p_wp)
    p_wp.set_defaults(fn=cmd_writeplane)

    p_info = sub.add_parser("info", help="resolved config + devices")
    _add_backend_flags(p_info)
    p_info.add_argument("--probe-timeout", type=float, default=20.0,
                        help="seconds to wait for device discovery before "
                        "reporting the backend unreachable (a dead "
                        "accelerator relay otherwise hangs forever)")
    # info never uses the fail-fast job probe; an explicitly-passed
    # --device-timeout is honored as the probe timeout instead of
    # silently ignored (None = flag not given).
    p_info.set_defaults(fn=cmd_info, device_timeout=None)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
