"""Spark adapter: run the TPU cascade inside ``rdd.mapPartitions``.

The reference IS a Spark job (reference heatmap.py:152-163); this
module is the compatibility bridge for shops whose ingest/orchestration
stays on Spark while the aggregation moves to TPU hosts (SURVEY.md §7
build-plan step 5, BASELINE.json's ``--backend=tpu`` north star). The
shape:

    rdd_of_row_dicts
      .mapPartitions(heatmap_partitions(config))   # TPU work per part.
      .reduceByKey(merge_heatmaps)                 # tiny blob merge
      -> (id, heatmap-json) pairs, reference output schema
         (reference heatmap.py:156-157)

Each partition runs the full projection+cascade on the local
accelerator and emits per-(user|timespan|coarse-tile) blob partials;
the shuffle then moves only aggregated blobs (kilobytes), not points —
the reference shuffles every point record twice per zoom level
(SURVEY.md §3.3, 32 shuffles).

Correctness rests on linearity: cascade(A ∪ B) == merge(cascade(A),
cascade(B)) per key, because every stage is a sum over points (tested
in tests/test_spark_adapter.py without a Spark cluster — the adapter
body is plain iterators, so pyspark is only needed at ``run_with_spark``
call time).
"""

from __future__ import annotations

import json


class HeatmapPartitionRunner:
    """The ``mapPartitions`` body: iterator of reference-shaped row
    dicts (latitude, longitude, user_id, source, timestamp — reference
    heatmap.py:25-36) in, ``(id, heatmap_json)`` pairs out.

    A module-level class so plain pickle (not just Spark's cloudpickle)
    can ship it to executors; configuration is captured as plain data
    and heatmap_tpu is imported lazily on the executor (which needs the
    package + jax installed).
    """

    def __init__(self, cfg_kwargs: dict):
        self.cfg_kwargs = cfg_kwargs

    def __call__(self, rows):
        from heatmap_tpu.pipeline import BatchJobConfig, run_batch

        blobs = run_batch(
            rows, BatchJobConfig(**self.cfg_kwargs), as_json=True
        )
        return iter(blobs.items())


def heatmap_partitions(config=None):
    """-> picklable callable for ``rdd.mapPartitions``."""
    return HeatmapPartitionRunner(_config_kwargs(config))


class HeatmapArrowRunner:
    """The ``DataFrame.mapInArrow`` body: iterator of
    ``pyarrow.RecordBatch`` with the reference columns in, RecordBatches
    of ``(id: string, heatmap: string)`` out.

    The Arrow boundary is the zero-copy Spark handoff (SURVEY.md §7
    "hard parts": don't drown the accelerator in per-row Python at the
    partition boundary): numeric columns cross as numpy views, only
    the user/source string columns materialize as Python lists, once
    per partition. The whole partition aggregates in ONE cascade call.
    """

    def __init__(self, cfg_kwargs: dict):
        self.cfg_kwargs = cfg_kwargs

    def __call__(self, batches):
        import numpy as np
        import pyarrow as pa

        from heatmap_tpu.pipeline import BatchJobConfig
        from heatmap_tpu.pipeline.batch import _run_loaded, load_columns

        lats, lons, users, stamps = [], [], [], []
        for rb in batches:
            d = {name: rb.column(name) for name in rb.schema.names}
            cols = load_columns({
                "latitude": d["latitude"].to_numpy(zero_copy_only=False),
                "longitude": d["longitude"].to_numpy(zero_copy_only=False),
                "user_id": d["user_id"].to_pylist() if "user_id" in d
                else [""] * rb.num_rows,
                "source": d["source"].to_pylist() if "source" in d else [],
                "timestamp": d["timestamp"].to_pylist()
                if "timestamp" in d else None,
            })
            lats.append(cols["latitude"])
            lons.append(cols["longitude"])
            users.extend(cols["user_id"])
            stamps.extend(cols["timestamp"])
        if not lats or sum(len(a) for a in lats) == 0:
            return
        blobs = _run_loaded(
            {
                "latitude": np.concatenate(lats),
                "longitude": np.concatenate(lons),
                "user_id": users,
                "timestamp": stamps,
            },
            BatchJobConfig(**self.cfg_kwargs),
            as_json=True,
        )
        # Explicit schema: an all-invalid partition yields zero blobs,
        # and from_pydict would otherwise infer null-typed columns that
        # Spark's schema check rejects. Emission is chunked because
        # string columns carry int32 offsets (2 GiB cap per column) —
        # a partition's concatenated JSON can exceed that.
        schema = pa.schema([("id", pa.string()), ("heatmap", pa.string())])
        ids = list(blobs.keys())
        vals = list(blobs.values())
        step = 1 << 18
        for lo in range(0, len(ids), step):
            yield pa.RecordBatch.from_pydict(
                {"id": ids[lo:lo + step], "heatmap": vals[lo:lo + step]},
                schema=schema,
            )


def heatmap_arrow_partitions(config=None):
    """-> picklable callable for ``DataFrame.mapInArrow(fn,
    'id string, heatmap string')``; partials still need the
    ``reduceByKey(merge_heatmaps)`` (or groupBy + UDF) merge since a
    blob's detail tiles can straddle partitions."""
    return HeatmapArrowRunner(_config_kwargs(config))


def merge_heatmaps(a: str, b: str) -> str:
    """reduceByKey merge: sum two heatmap-json blobs per detail tile."""
    da, db = json.loads(a), json.loads(b)
    for k, v in db.items():
        da[k] = da.get(k, 0) + v
    return json.dumps(da)


def run_with_spark(rdd, config=None, output_table=None):
    """Driver-side orchestration over a live RDD (needs pyspark).

    With ``output_table`` the reduced pairs are written straight from
    the executors as a DataFrame ``(id, heatmap)`` in the reference's
    Cassandra append shape (reference heatmap.py:149-150,157) — the
    result set never funnels through the driver — and None is
    returned. Without it, the blobs are collected and returned as a
    dict (small-result / interactive use).
    """
    pairs = rdd.mapPartitions(heatmap_partitions(config)).reduceByKey(
        merge_heatmaps
    )
    if output_table is not None:
        # createDataFrame over the pairs RDD is a distributed write
        # plan (no driver collect); getOrCreate also covers legacy
        # SparkContext-only jobs where RDD.toDF is not yet patched in.
        from pyspark.sql import SparkSession

        spark = SparkSession.builder.getOrCreate()
        df = spark.createDataFrame(pairs, ["id", "heatmap"])
        (
            df.write.format("org.apache.spark.sql.cassandra")
            .mode("append")
            .options(**output_table)
            .save()
        )
        return None
    return dict(pairs.collect())


def simulate_partitions(partitions, config=None):
    """Run the exact mapPartitions/reduceByKey dataflow on in-memory
    lists (no Spark) — the test/validation harness for the adapter."""
    fn = heatmap_partitions(config)
    merged: dict = {}
    for part in partitions:
        for key, blob in fn(iter(part)):
            merged[key] = (
                merge_heatmaps(merged[key], blob) if key in merged else blob
            )
    return merged


def _config_kwargs(config) -> dict:
    if config is None:
        return {}
    import dataclasses

    if dataclasses.is_dataclass(config):
        return dataclasses.asdict(config)
    return dict(config)
