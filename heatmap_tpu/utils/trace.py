"""Tracing and throughput metrics.

The reference has zero instrumentation — no timers, no logging, not one
print (SURVEY.md §5 "tracing/profiling: none in-repo"; the ``time``
import at reference heatmap.py:10 is unused). This module provides the
greenfield replacement:

- ``span(name)`` — wall-clock span timer, nestable, recorded into a
  process-wide ``Tracer`` (per-name count / total / max).
- ``Tracer.add_items(name, n)`` — throughput accounting: items
  processed under a name, so ``report()`` yields points/sec.
- ``jax_profile(logdir)`` — context manager around ``jax.profiler``'s
  trace (TensorBoard-viewable XLA timeline), gated so CPU-only test
  environments without profiler support degrade to a no-op.

Spans measure *host* wall-clock. For device work inside a span, call
``block_until_ready`` on the result before the span closes, or the
span records only dispatch time (XLA is async).

Closed spans also feed the telemetry subsystem (heatmap_tpu/obs): a
``stage_duration_seconds`` histogram sample plus a ``stage_end`` event —
both no-ops unless a metrics sink or event log is configured, so the
tracer stays usable standalone.
"""

from __future__ import annotations

import contextlib
import threading
import time

_obs = None  # lazily imported so importing trace never pulls in obs/jax

# Span-tree hooks, installed by obs.tracing.enable_tracing (and removed
# by disable_tracing). While set, every default-tracer span also opens a
# node in the hierarchical trace (obs/tracing.py) — the aggregate API
# here is unchanged, and with tracing off the cost is one global read.
_tree_begin = None
_tree_end = None


def _obs_record(name: str, wall_s: float, items, attrs: dict):
    global _obs
    if _obs is None:
        from heatmap_tpu import obs

        _obs = obs
    _obs.record_stage(name, wall_s, items=items, **attrs)


class _SpanStats:
    __slots__ = ("count", "total_s", "max_s", "items")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.items = 0


class Tracer:
    """Per-name span statistics + item throughput, thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: dict[str, _SpanStats] = {}
        # Set by jax_profile when the profiler cannot start; surfaced
        # in obs.report.build_run_report's warnings.
        self.profiler_warning: str | None = None

    def _stat(self, name: str) -> _SpanStats:
        s = self._stats.get(name)
        if s is None:
            s = self._stats.setdefault(name, _SpanStats())
        return s

    @contextlib.contextmanager
    def span(self, name: str, items: int | None = None, **attrs):
        """Extra keyword attrs (e.g. ``backend="partitioned"``) ride
        along on the stage_end event when an event log is installed."""
        begin = _tree_begin
        tree_span = (begin(name, attrs or None)
                     if begin is not None and self is _default else None)
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                s = self._stat(name)
                s.count += 1
                s.total_s += dt
                s.max_s = max(s.max_s, dt)
                if items:
                    s.items += int(items)
            if self is _default:
                # stage_end emits while the tree span is still ambient,
                # so the event is stamped with this span's identity.
                _obs_record(name, dt, items, attrs)
            if tree_span is not None:
                end = _tree_end
                if end is not None:  # may be unhooked mid-span in tests
                    end(tree_span)

    def add_items(self, name: str, n: int):
        """Attribute ``n`` processed items to ``name`` (throughput)."""
        with self._lock:
            self._stat(name).items += int(n)

    def report(self) -> dict:
        """{name: {count, total_s, max_s, mean_s, items, items_per_s}}."""
        out = {}
        with self._lock:
            for name, s in self._stats.items():
                out[name] = {
                    "count": s.count,
                    "total_s": s.total_s,
                    "max_s": s.max_s,
                    "mean_s": s.total_s / s.count if s.count else 0.0,
                    "items": s.items,
                    "items_per_s": s.items / s.total_s if s.total_s else 0.0,
                }
        return out

    def reset(self):
        with self._lock:
            self._stats.clear()
            self.profiler_warning = None

    def format_report(self) -> str:
        lines = []
        for name, r in sorted(self.report().items()):
            line = (
                f"{name:<28} n={r['count']:<6} total={r['total_s']:.3f}s "
                f"mean={r['mean_s'] * 1e3:.2f}ms max={r['max_s'] * 1e3:.2f}ms"
            )
            if r["items"]:
                line += (
                    f" items={r['items']} ({r['items_per_s'] / 1e6:.2f} M/s)"
                )
            lines.append(line)
        return "\n".join(lines)


_default = Tracer()


def get_tracer() -> Tracer:
    """The process-wide tracer the pipeline instruments into."""
    return _default


def span(name: str, items: int | None = None, **attrs):
    """Span on the default tracer: ``with span("binning", items=n): ...``"""
    return _default.span(name, items=items, **attrs)


# -- per-stage cascade attribution (opt-in diagnostic) ---------------------
#
# The production cascade runs under ONE jit (pipeline/cascade.py
# _build_cascade_jit), so host spans inside it would time tracing, not
# execution. Stage tracing is a global opt-in (bench_job --trace-stages)
# that (a) makes the pipeline run the cascade EAGERLY and (b) turns the
# stage_span/stage_block call sites inside the kernels into real
# blocked measurements (sort / segment-reduce / decode / host egress).
# Off (the default) both helpers are free: a nullcontext and identity.

_stage_tracing = False


def enable_stage_tracing(on: bool = True):
    global _stage_tracing
    _stage_tracing = on


def stage_tracing_enabled() -> bool:
    return _stage_tracing


def stage_span(name: str, items: int | None = None, **attrs):
    """A tracer span only under stage tracing; nullcontext otherwise
    (kernels call this on hot paths — it must cost nothing when off)."""
    if not _stage_tracing:
        return contextlib.nullcontext()
    return _default.span(name, items=items, **attrs)


def stage_block(x):
    """block_until_ready under stage tracing (a span closing on an
    unblocked async dispatch records ~0), identity otherwise. Safe on
    tracers: if the value cannot block (a traced caller slipped
    through), it is returned unchanged."""
    if not _stage_tracing:
        return x
    try:
        import jax

        return jax.block_until_ready(x)
    except Exception:  # noqa: BLE001 — tracing/abstract values
        return x


@contextlib.contextmanager
def jax_profile(logdir: str):
    """Capture a jax.profiler trace (XLA timeline) into ``logdir``.

    No-op when the profiler is unavailable on the current backend: the
    failure is recorded on ``get_tracer().profiler_warning`` and, when
    an event log is installed, as a ``profiler_unavailable`` event —
    both surface in the run report's warnings.
    """
    import jax

    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as e:
        started = False
        _default.profiler_warning = (
            f"jax profiler unavailable ({type(e).__name__}: {e}); "
            f"no trace written to {logdir}")
        try:
            from heatmap_tpu.obs import events as _events

            _events.emit("profiler_unavailable", error=repr(e),
                         logdir=str(logdir))
        except Exception:
            pass
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
