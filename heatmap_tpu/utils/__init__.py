"""Auxiliary runtime subsystems: tracing, checkpointing, recovery.

The reference had none of these in-repo — profiling was the Spark UI,
fault tolerance was Spark lineage recomputation, and there was no
checkpoint/resume at all (SURVEY.md §5). Here they are first-class:

- ``trace`` — span timers, throughput counters, jax.profiler hooks.
- ``checkpoint`` — atomic npz checkpoints with a retention manager.
- ``recovery`` — deterministic shard re-execution with retry budgets
  and fault injection for tests.
"""

from heatmap_tpu.utils.trace import (  # noqa: F401
    Tracer,
    get_tracer,
    jax_profile,
    span,
)
from heatmap_tpu.utils.checkpoint import (  # noqa: F401
    CheckpointManager,
    fsync_dir,
    load_checkpoint,
    publish_dir,
    save_checkpoint,
)
from heatmap_tpu.utils.recovery import (  # noqa: F401
    FaultInjector,
    ShardFailure,
    run_shards,
)
