"""Atomic checkpoints with retention.

The reference has no checkpoint/resume at all: a 16-level unioned Spark
lineage is recomputed from source on failure, and its Cassandra write
mode 'append' makes reruns upsert blindly (SURVEY.md §5, reference
heatmap.py:113-116,150). Here checkpoints are explicit:

- ``save_checkpoint`` writes arrays + JSON-serializable meta as one npz
  via write-to-temp + atomic rename, so a crash mid-write never leaves
  a truncated checkpoint behind.
- ``CheckpointManager`` numbers checkpoints by step, finds the latest,
  and prunes old ones (keep-N retention).

Rasters and cascade partials are pure sums, so resuming from any saved
step and re-adding the remaining shards is idempotent-by-construction
(the recovery model SURVEY.md §5 prescribes for the TPU build).
"""

from __future__ import annotations

import json
import os
import re
import tempfile

import numpy as np

_META_KEY = "__meta_json__"
_STEP_RE = re.compile(r"^ckpt-(\d+)\.npz$")


def fsync_dir(path: str):
    """fsync a directory so a just-renamed entry survives power loss.

    Best-effort: platforms/filesystems that refuse O_RDONLY directory
    fds (or directory fsync entirely) degrade to the pre-fsync
    behavior rather than failing the publish.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def publish_dir(tmp_path: str, final_path: str):
    """Durably publish a staged directory: fsync every file it holds,
    rename ``tmp_path`` -> ``final_path``, then fsync the parent so the
    rename itself is on disk — the directory-shaped counterpart of
    ``save_checkpoint``'s tmp+fsync+replace contract. ``final_path``
    must not exist (a recovery sweep quarantines stale orphans first;
    see delta/recover.py) — checked explicitly, because POSIX rename
    onto an empty directory would silently succeed."""
    if os.path.exists(final_path):
        raise FileExistsError(
            f"publish target {final_path!r} already exists; run the "
            "recovery sweep (delta/recover.py) to quarantine it first")
    for dirpath, dirnames, filenames in os.walk(tmp_path):
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            fd = os.open(full, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        for name in sorted(dirnames):
            fsync_dir(os.path.join(dirpath, name))
    fsync_dir(tmp_path)
    os.rename(tmp_path, final_path)
    fsync_dir(os.path.dirname(os.path.abspath(final_path)))


def save_checkpoint(path: str, arrays: dict, meta: dict | None = None):
    """Atomically write ``arrays`` (+ JSON ``meta``) to ``path`` (.npz):
    write-to-temp, fsync, ``os.replace``, parent-dir fsync."""
    for k in arrays:
        if k == _META_KEY:
            raise ValueError(f"array name {k!r} is reserved")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    payload[_META_KEY] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8
    )
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_checkpoint(path: str) -> tuple[dict, dict]:
    """-> (arrays, meta)."""
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
        meta = json.loads(bytes(z[_META_KEY].tobytes()).decode()) \
            if _META_KEY in z.files else {}
    return arrays, meta


class CheckpointManager:
    """Step-numbered checkpoints in a directory, keep-N retention."""

    def __init__(self, directory: str, keep: int = 3):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt-{step}.npz")

    def steps(self) -> list[int]:
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            # Directory removed by a concurrent maintenance pass —
            # same answer as an empty directory.
            return []
        out = []
        for name in names:
            m = _STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        s = self.steps()
        return s[-1] if s else None

    def save(self, step: int, arrays: dict, meta: dict | None = None) -> str:
        meta = dict(meta or {})
        meta["step"] = step
        path = self._path(step)
        save_checkpoint(path, arrays, meta)
        self._prune()
        return path

    def load(self, step: int | None = None) -> tuple[dict, dict]:
        """Load ``step`` (default: latest). Raises FileNotFoundError if
        there is nothing to load."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        return load_checkpoint(self._path(step))

    def prune(self, keep: int | None = None):
        """Delete all but the newest ``keep`` checkpoints (default:
        the manager's retention).

        Robust to a concurrent maintenance pass racing us: a file that
        vanishes between the listing and the unlink is somebody else's
        successful deletion, not a failure — skip it and keep pruning
        the rest. ``keep=0`` deletes everything (the delta journal's
        retention pass uses this once every entry has been folded into
        a compacted base).
        """
        keep = self.keep if keep is None else keep
        if keep < 0:
            raise ValueError("keep must be >= 0")
        steps = self.steps()
        doomed = steps[:-keep] if keep else steps
        for s in doomed:
            try:
                os.unlink(self._path(s))
            except FileNotFoundError:
                continue  # concurrently deleted — keep pruning
            except OSError:
                continue

    def _prune(self):
        self.prune()
