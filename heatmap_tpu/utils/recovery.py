"""Deterministic shard re-execution with retry budgets.

The reference's fault tolerance is entirely Spark's: lineage
recomputation of lost RDD partitions plus dynamic executor allocation
(SURVEY.md §5; reference submit-heatmap:10-13). The TPU-native model is
simpler and stronger: ingest is split into deterministic shards (file
byte ranges, Cassandra token ranges, synthetic seed ranges), every
shard's contribution is a pure sum, and a failed shard is simply re-run
— re-adding an identical partial is the only way a retry can land, so
recovery is idempotent by construction.

Fault injection: every attempt runs the process-wide plane's
``shard.compute`` check (heatmap_tpu/faults/), and the legacy
``FaultInjector`` — kept as the stable test/chaos API — is now a thin
wrapper over a private plane with per-shard count rules. Backoff
follows the unified policy (bounded exponential + full jitter,
deterministic under an installed plane's seed); the sleep itself lives
in ``faults.retry.sleep_backoff``, keeping this module free of
hand-rolled retry sleeps.
"""

from __future__ import annotations

import time

from heatmap_tpu import faults


class ShardFailure(RuntimeError):
    """A shard exhausted its retry budget."""

    def __init__(self, shard_index, attempts, last_error):
        super().__init__(
            f"shard {shard_index} failed after {attempts} attempts: "
            f"{last_error!r}"
        )
        self.shard_index = shard_index
        self.attempts = attempts
        self.last_error = last_error


class FaultInjector:
    """Deterministically fail chosen shards N times (for tests/chaos).

    ``fail_counts``: {shard_index: times_to_fail}. Call ``check(i)``
    at the top of shard work; it raises until shard i's budget is
    spent, then lets the shard through — modeling a transient fault.

    Implemented as per-shard count rules on a private
    :class:`heatmap_tpu.faults.FaultPlane` (the ``shard.compute`` site),
    so the legacy API and the chaos plane share one injection engine.
    """

    def __init__(self, fail_counts: dict):
        self._plane = faults.FaultPlane()
        for shard_index, times in fail_counts.items():
            if times > 0:
                self._plane.add_rule("shard.compute", key=shard_index,
                                     count=int(times))

    @property
    def injected(self) -> int:
        return self._plane.injected

    def check(self, shard_index):
        self._plane.check("shard.compute", key=shard_index)


def run_shards(shards, process, *, retries: int = 2, backoff_s: float = 0.0,
               backoff_cap_s: float = 2.0, deadline_s: float | None = None,
               fault_injector: FaultInjector | None = None,
               on_retry=None, tracer=None, max_workers: int = 1,
               fallback=None, speculate_factor: float | None = None,
               speculate_quantile: float = 0.75, on_speculate=None):
    """Run ``process(shard)`` over every shard with per-shard retries.

    Returns the list of per-shard results in shard order (order is
    deterministic regardless of failures or concurrency — the analog
    of Spark's deterministic partition recompute). ``retries`` is the
    number of *re*-executions allowed per shard; ``on_retry(i,
    attempt, err)`` is the failure-detection hook (log, mark executor
    unhealthy, ...). Raises ShardFailure once a shard exhausts its
    budget.

    Backoff before retry ``k`` is full-jitter exponential:
    ``min(backoff_cap_s, backoff_s * 2**(k-1)) * U`` with deterministic
    jitter U (see faults/retry.py); ``backoff_s=0`` (the default)
    disables sleeping. ``deadline_s`` bounds one shard's total
    failure+backoff window — exceeding it fails the shard even with
    retry budget left.

    ``max_workers > 1`` runs shards on a thread pool — the right shape
    for IO-bound shards like Cassandra token-range or CosmosDB
    partition-range scans, which spend their time off-GIL in sockets.
    Retry bookkeeping is per shard and thread-local; ``on_retry`` may
    be called concurrently and must be thread-safe. On the first
    ShardFailure, outstanding (not-yet-started) shards are cancelled
    rather than left to run behind the raised error.

    Two elastic-execution hooks (parallel/elastic.py is the full
    coordinator; these are the run_shards-level primitives):

    - ``fallback(i, shard, last_error)`` — failover re-execution:
      called *instead of raising ShardFailure* once shard ``i``
      exhausts its local budget; its return value becomes the shard's
      result (the hook re-runs the shard elsewhere, serves a cached
      partial, ...). Exceptions from the hook propagate unwrapped.
    - ``speculate_factor`` — speculative straggler duplication (pool
      path only): once at least three shards have completed, a still-
      running shard whose elapsed time exceeds ``speculate_factor`` x
      the ``speculate_quantile``-quantile of completed durations is
      submitted a second time; first completion wins, the duplicate's
      identical result is discarded (shards are deterministic, so
      either result is THE result). ``on_speculate(i, elapsed_s,
      threshold_s)`` observes each launch.
    """

    from heatmap_tpu import obs

    def run_one(i, shard):
        attempt = 0
        started = time.monotonic()
        while True:
            try:
                if fault_injector is not None:
                    fault_injector.check(i)
                faults.check("shard.compute", key=i)
                if tracer is not None:
                    with tracer.span("shard"):
                        result = process(shard)
                else:
                    result = process(shard)
            except Exception as e:  # noqa: BLE001 — retry boundary
                attempt += 1
                obs.record_retry(i, attempt, e)
                if on_retry is not None:
                    on_retry(i, attempt, e)
                exhausted = attempt > retries or (
                    deadline_s is not None
                    and time.monotonic() - started >= deadline_s)
                if exhausted:
                    if fallback is not None:
                        return fallback(i, shard, e)
                    raise ShardFailure(i, attempt, e) from e
                if backoff_s:
                    faults.sleep_backoff("shard.compute", i, attempt,
                                         base_s=backoff_s,
                                         cap_s=backoff_cap_s)
            else:
                if attempt:
                    # The shard landed after at least one failure —
                    # the recovery event the retry events pair with.
                    obs.record_recovery(i, attempt)
                return result

    shards = list(shards)
    if max_workers <= 1:
        return [run_one(i, s) for i, s in enumerate(shards)]
    if speculate_factor is not None:
        return _run_shards_speculative(
            shards, run_one, max_workers=max_workers,
            speculate_factor=speculate_factor,
            speculate_quantile=speculate_quantile,
            on_speculate=on_speculate)
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=max_workers) as ex:
        futures = [ex.submit(run_one, i, s) for i, s in enumerate(shards)]
        # In-order collection keeps results deterministic; the first
        # exhausted shard raises after cancelling every shard that has
        # not started yet (already-running shards finish their attempt
        # inside the pool's shutdown wait).
        try:
            return [f.result() for f in futures]
        except BaseException:
            for f in futures:
                f.cancel()
            raise


#: Completed-shard sample needed before speculation can trigger.
_MIN_SPECULATION_SAMPLES = 3


def _run_shards_speculative(shards, run_one, *, max_workers: int,
                            speculate_factor: float,
                            speculate_quantile: float, on_speculate):
    """Pool execution with straggler duplication (first-completion-wins).

    Every attempt goes through the same ``run_one`` (full retry
    bookkeeping); a per-shard resolution flag makes the first finisher
    the winner and turns the loser's ShardFailure (if any) into a
    no-op — a duplicate must never fail a shard its twin completed.
    """
    import threading
    from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
    from concurrent.futures import wait as _fwait

    n = len(shards)
    results = [None] * n
    resolved = [False] * n
    started: dict = {}  # shard -> first actual start (not submit) time
    durations: list = []
    lock = threading.Lock()

    def run_resolved(i, shard):
        now = time.monotonic()
        with lock:
            started.setdefault(i, now)
        try:
            r = run_one(i, shard)
        except ShardFailure:
            with lock:
                if resolved[i]:
                    return  # the twin already won; this loss is moot
            raise
        with lock:
            if not resolved[i]:
                resolved[i] = True
                results[i] = r
                durations.append(time.monotonic() - now)

    with ThreadPoolExecutor(max_workers=max_workers) as ex:
        pending = {ex.submit(run_resolved, i, s)
                   for i, s in enumerate(shards)}
        speculated: set = set()
        try:
            while pending:
                done, pending = _fwait(pending, timeout=0.05,
                                       return_when=FIRST_COMPLETED)
                for f in done:
                    f.result()
                with lock:
                    dur = sorted(durations)
                    snapshot = dict(started)
                    unresolved = [i for i in range(n) if not resolved[i]]
                if len(dur) < _MIN_SPECULATION_SAMPLES:
                    continue
                q = min(max(float(speculate_quantile), 0.0), 1.0)
                threshold = speculate_factor * dur[int(q * (len(dur) - 1))]
                now = time.monotonic()
                for i in unresolved:
                    if i in speculated or i not in snapshot:
                        continue
                    elapsed = now - snapshot[i]
                    if elapsed <= threshold:
                        continue
                    speculated.add(i)
                    if on_speculate is not None:
                        on_speculate(i, elapsed, threshold)
                    pending.add(ex.submit(run_resolved, i, shards[i]))
        except BaseException:
            for f in pending:
                f.cancel()
            raise
    return results
