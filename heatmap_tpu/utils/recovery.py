"""Deterministic shard re-execution with retry budgets.

The reference's fault tolerance is entirely Spark's: lineage
recomputation of lost RDD partitions plus dynamic executor allocation
(SURVEY.md §5; reference submit-heatmap:10-13). The TPU-native model is
simpler and stronger: ingest is split into deterministic shards (file
byte ranges, Cassandra token ranges, synthetic seed ranges), every
shard's contribution is a pure sum, and a failed shard is simply re-run
— re-adding an identical partial is the only way a retry can land, so
recovery is idempotent by construction.

``FaultInjector`` provides the fault-injection hook the reference never
had: tests (and chaos runs) fail chosen shards a chosen number of times
to exercise the retry/recovery path.
"""

from __future__ import annotations

import time


class ShardFailure(RuntimeError):
    """A shard exhausted its retry budget."""

    def __init__(self, shard_index, attempts, last_error):
        super().__init__(
            f"shard {shard_index} failed after {attempts} attempts: "
            f"{last_error!r}"
        )
        self.shard_index = shard_index
        self.attempts = attempts
        self.last_error = last_error


class FaultInjector:
    """Deterministically fail chosen shards N times (for tests/chaos).

    ``fail_counts``: {shard_index: times_to_fail}. Call ``check(i)``
    at the top of shard work; it raises until shard i's budget is
    spent, then lets the shard through — modeling a transient fault.
    """

    def __init__(self, fail_counts: dict):
        import threading

        self._remaining = dict(fail_counts)
        self._lock = threading.Lock()  # run_shards may be threaded
        self.injected = 0

    def check(self, shard_index):
        with self._lock:
            left = self._remaining.get(shard_index, 0)
            if left <= 0:
                return
            self._remaining[shard_index] = left - 1
            self.injected += 1
        raise RuntimeError(f"injected fault on shard {shard_index}")


def run_shards(shards, process, *, retries: int = 2, backoff_s: float = 0.0,
               fault_injector: FaultInjector | None = None,
               on_retry=None, tracer=None, max_workers: int = 1):
    """Run ``process(shard)`` over every shard with per-shard retries.

    Returns the list of per-shard results in shard order (order is
    deterministic regardless of failures or concurrency — the analog
    of Spark's deterministic partition recompute). ``retries`` is the
    number of *re*-executions allowed per shard; ``on_retry(i,
    attempt, err)`` is the failure-detection hook (log, mark executor
    unhealthy, ...). Raises ShardFailure once a shard exhausts its
    budget.

    ``max_workers > 1`` runs shards on a thread pool — the right shape
    for IO-bound shards like Cassandra token-range or CosmosDB
    partition-range scans, which spend their time off-GIL in sockets.
    Retry bookkeeping is per shard and thread-local; ``on_retry`` may
    be called concurrently and must be thread-safe.
    """

    from heatmap_tpu import obs

    def run_one(i, shard):
        attempt = 0
        while True:
            try:
                if fault_injector is not None:
                    fault_injector.check(i)
                if tracer is not None:
                    with tracer.span("shard"):
                        result = process(shard)
                else:
                    result = process(shard)
            except Exception as e:  # noqa: BLE001 — retry boundary
                attempt += 1
                obs.record_retry(i, attempt, e)
                if on_retry is not None:
                    on_retry(i, attempt, e)
                if attempt > retries:
                    raise ShardFailure(i, attempt, e) from e
                if backoff_s:
                    time.sleep(backoff_s * attempt)
            else:
                if attempt:
                    # The shard landed after at least one failure —
                    # the recovery event the retry events pair with.
                    obs.record_recovery(i, attempt)
                return result

    shards = list(shards)
    if max_workers <= 1:
        return [run_one(i, s) for i, s in enumerate(shards)]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=max_workers) as ex:
        futures = [ex.submit(run_one, i, s) for i, s in enumerate(shards)]
        # In-order collection keeps results deterministic; the first
        # exhausted shard raises (others complete or are abandoned with
        # the pool).
        return [f.result() for f in futures]
