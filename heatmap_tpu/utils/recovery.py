"""Deterministic shard re-execution with retry budgets.

The reference's fault tolerance is entirely Spark's: lineage
recomputation of lost RDD partitions plus dynamic executor allocation
(SURVEY.md §5; reference submit-heatmap:10-13). The TPU-native model is
simpler and stronger: ingest is split into deterministic shards (file
byte ranges, Cassandra token ranges, synthetic seed ranges), every
shard's contribution is a pure sum, and a failed shard is simply re-run
— re-adding an identical partial is the only way a retry can land, so
recovery is idempotent by construction.

Fault injection: every attempt runs the process-wide plane's
``shard.compute`` check (heatmap_tpu/faults/), and the legacy
``FaultInjector`` — kept as the stable test/chaos API — is now a thin
wrapper over a private plane with per-shard count rules. Backoff
follows the unified policy (bounded exponential + full jitter,
deterministic under an installed plane's seed); the sleep itself lives
in ``faults.retry.sleep_backoff``, keeping this module free of
hand-rolled retry sleeps.
"""

from __future__ import annotations

import time

from heatmap_tpu import faults


class ShardFailure(RuntimeError):
    """A shard exhausted its retry budget."""

    def __init__(self, shard_index, attempts, last_error):
        super().__init__(
            f"shard {shard_index} failed after {attempts} attempts: "
            f"{last_error!r}"
        )
        self.shard_index = shard_index
        self.attempts = attempts
        self.last_error = last_error


class FaultInjector:
    """Deterministically fail chosen shards N times (for tests/chaos).

    ``fail_counts``: {shard_index: times_to_fail}. Call ``check(i)``
    at the top of shard work; it raises until shard i's budget is
    spent, then lets the shard through — modeling a transient fault.

    Implemented as per-shard count rules on a private
    :class:`heatmap_tpu.faults.FaultPlane` (the ``shard.compute`` site),
    so the legacy API and the chaos plane share one injection engine.
    """

    def __init__(self, fail_counts: dict):
        self._plane = faults.FaultPlane()
        for shard_index, times in fail_counts.items():
            if times > 0:
                self._plane.add_rule("shard.compute", key=shard_index,
                                     count=int(times))

    @property
    def injected(self) -> int:
        return self._plane.injected

    def check(self, shard_index):
        self._plane.check("shard.compute", key=shard_index)


def run_shards(shards, process, *, retries: int = 2, backoff_s: float = 0.0,
               backoff_cap_s: float = 2.0, deadline_s: float | None = None,
               fault_injector: FaultInjector | None = None,
               on_retry=None, tracer=None, max_workers: int = 1):
    """Run ``process(shard)`` over every shard with per-shard retries.

    Returns the list of per-shard results in shard order (order is
    deterministic regardless of failures or concurrency — the analog
    of Spark's deterministic partition recompute). ``retries`` is the
    number of *re*-executions allowed per shard; ``on_retry(i,
    attempt, err)`` is the failure-detection hook (log, mark executor
    unhealthy, ...). Raises ShardFailure once a shard exhausts its
    budget.

    Backoff before retry ``k`` is full-jitter exponential:
    ``min(backoff_cap_s, backoff_s * 2**(k-1)) * U`` with deterministic
    jitter U (see faults/retry.py); ``backoff_s=0`` (the default)
    disables sleeping. ``deadline_s`` bounds one shard's total
    failure+backoff window — exceeding it fails the shard even with
    retry budget left.

    ``max_workers > 1`` runs shards on a thread pool — the right shape
    for IO-bound shards like Cassandra token-range or CosmosDB
    partition-range scans, which spend their time off-GIL in sockets.
    Retry bookkeeping is per shard and thread-local; ``on_retry`` may
    be called concurrently and must be thread-safe. On the first
    ShardFailure, outstanding (not-yet-started) shards are cancelled
    rather than left to run behind the raised error.
    """

    from heatmap_tpu import obs

    def run_one(i, shard):
        attempt = 0
        started = time.monotonic()
        while True:
            try:
                if fault_injector is not None:
                    fault_injector.check(i)
                faults.check("shard.compute", key=i)
                if tracer is not None:
                    with tracer.span("shard"):
                        result = process(shard)
                else:
                    result = process(shard)
            except Exception as e:  # noqa: BLE001 — retry boundary
                attempt += 1
                obs.record_retry(i, attempt, e)
                if on_retry is not None:
                    on_retry(i, attempt, e)
                if attempt > retries:
                    raise ShardFailure(i, attempt, e) from e
                if (deadline_s is not None
                        and time.monotonic() - started >= deadline_s):
                    raise ShardFailure(i, attempt, e) from e
                if backoff_s:
                    faults.sleep_backoff("shard.compute", i, attempt,
                                         base_s=backoff_s,
                                         cap_s=backoff_cap_s)
            else:
                if attempt:
                    # The shard landed after at least one failure —
                    # the recovery event the retry events pair with.
                    obs.record_recovery(i, attempt)
                return result

    shards = list(shards)
    if max_workers <= 1:
        return [run_one(i, s) for i, s in enumerate(shards)]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=max_workers) as ex:
        futures = [ex.submit(run_one, i, s) for i, s in enumerate(shards)]
        # In-order collection keeps results deterministic; the first
        # exhausted shard raises after cancelling every shard that has
        # not started yet (already-running shards finish their attempt
        # inside the pool's shutdown wait).
        try:
            return [f.result() for f in futures]
        except BaseException:
            for f in futures:
                f.cancel()
            raise
