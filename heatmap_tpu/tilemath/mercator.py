"""Vectorized Web-Mercator projection (forward and inverse).

Behavioral contract — matches the reference scalar math exactly
(reference tile.py:16-30), including its quirks (SURVEY.md §8.5):

- ``floor`` semantics (round toward -inf), not truncation, so negative
  intermediate values round *down* (reference tile.py:17,21).
- **No pole clamping**: |lat| >= 90 yields non-finite rows; latitudes
  beyond ±85.0511° yield rows outside [0, 2^zoom).
- **No antimeridian wrap**: lon == 180 yields column == 2^zoom.

Out-of-range / non-finite results are *reported* via ``project_points``'s
validity mask rather than silently clamped, so callers choose the policy.

Precision policy (SURVEY.md §7 "hard parts"): the fractional Mercator y
needs ~zoom+2 bits of mantissa for correct binning at zoom z. float32
(24-bit mantissa) is safe through z≈15 away from tile boundaries and is
the fast TPU path; float64 (requires ``jax_enable_x64``) reproduces the
CPython-double reference semantics through z21 and is the default when
x64 is enabled. Measured on v5e-1 (PERF_NOTES.md round 2): emulated
f64 projection runs at 0.31 B pts/s (~1.8x the f32 cost) and is
bit-identical to the CPython-double oracle at z21, while f32 misbins
~86% of points at z21 — so detail-zoom device binning should always
run under x64; no split-precision kernel is needed.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

# Latitude of the square Web-Mercator world edge: atan(sinh(pi)). Used by
# data generators and validity docs; the projection itself never clamps.
MAX_LATITUDE = math.degrees(math.atan(math.sinh(math.pi)))  # 85.05112877980659

_PI = math.pi


def default_float_dtype():
    """float64 when x64 is enabled (exact reference semantics), else float32."""
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _as_float(x, dtype):
    dtype = dtype or default_float_dtype()
    return jnp.asarray(x, dtype=dtype)


def mercator_y(latitude, dtype=None):
    """Normalized Mercator y in [0, 1) for latitudes in the mercator range.

    Operation order mirrors the reference formula (reference tile.py:17)
    so float64 results agree with CPython doubles:
    ``(1 - log(tan(phi) + sec(phi)) / pi) / 2`` with ``phi = lat*pi/180``.
    """
    lat = _as_float(latitude, dtype)
    phi = lat * _PI / 180.0
    return (1.0 - jnp.log(jnp.tan(phi) + 1.0 / jnp.cos(phi)) / _PI) / 2.0


def mercator_x(longitude, dtype=None):
    """Normalized Mercator x in [0, 1); lon == 180 maps to exactly 1.0."""
    lon = _as_float(longitude, dtype)
    return (lon + 180.0) / 360.0


def row_from_latitude(latitude, zoom, dtype=None):
    """Floored tile row at ``zoom`` (float dtype; may be non-finite at poles).

    Matches reference tile.py:16-17.
    """
    return jnp.floor(mercator_y(latitude, dtype) * float(1 << zoom))


def column_from_longitude(longitude, zoom, dtype=None):
    """Floored tile column at ``zoom`` (float dtype; 180° -> 2^zoom).

    Matches reference tile.py:20-21.
    """
    return jnp.floor(mercator_x(longitude, dtype) * float(1 << zoom))


def latitude_from_row(row, zoom, dtype=None):
    """North-edge latitude of tile ``row`` at ``zoom``.

    Matches reference tile.py:24-26: ``atan(sinh(n))`` written as
    ``atan(0.5*(e^n - e^-n))`` with ``n = pi - 2*pi*row/2^zoom``.
    """
    r = _as_float(row, dtype)
    n = _PI - 2.0 * _PI * r / float(1 << zoom)
    return 180.0 / _PI * jnp.arctan(0.5 * (jnp.exp(n) - jnp.exp(-n)))


def longitude_from_column(column, zoom, dtype=None):
    """West-edge longitude of tile ``column`` at ``zoom`` (reference tile.py:29-30)."""
    c = _as_float(column, dtype)
    return c / float(1 << zoom) * 360.0 - 180.0


def project_points(latitude, longitude, zoom, dtype=None):
    """Project point arrays to integer (row, col) at ``zoom`` with validity.

    Returns ``(row, col, valid)`` where row/col are int32 (rows/cols fit
    int32 for every zoom <= 30) and ``valid`` marks points whose row and
    column are finite and inside [0, 2^zoom) — the vectorized analog of
    the reference's implicit "garbage in, garbage out" behavior
    (SURVEY.md §8.5), made explicit so kernels can mask instead of crash.
    """
    n = float(1 << zoom)
    frow = row_from_latitude(latitude, zoom, dtype)
    fcol = column_from_longitude(longitude, zoom, dtype)
    valid = (
        jnp.isfinite(frow)
        & jnp.isfinite(fcol)
        & (frow >= 0.0)
        & (frow < n)
        & (fcol >= 0.0)
        & (fcol < n)
    )
    # Zero out invalid lanes before the int cast: clip alone propagates
    # NaN, and NaN->int is backend-dependent garbage. Invalid points are
    # excluded by the mask; the zeroing just guarantees in-range indices
    # for masked scatters.
    frow = jnp.where(valid, frow, 0.0)
    fcol = jnp.where(valid, fcol, 0.0)
    row = jnp.clip(frow, 0.0, n - 1.0).astype(jnp.int32)
    col = jnp.clip(fcol, 0.0, n - 1.0).astype(jnp.int32)
    return row, col, valid


def project_points_np(latitude, longitude, zoom):
    """Host-side numpy-f64 projection: -> (row, col, valid) int64/bool.

    The exact-precision host path (same operation order as the jnp
    version above and reference tile.py:17,21); used by the batch
    pipeline so device dtype policy can't affect ingest binning.
    """
    import numpy as np

    n = 1 << zoom
    lat = np.asarray(latitude, np.float64)
    lon = np.asarray(longitude, np.float64)
    with np.errstate(all="ignore"):
        phi = lat * _PI / 180
        y = (1 - np.log(np.tan(phi) + 1 / np.cos(phi)) / _PI) / 2
        frow = np.floor(y * n)
        fcol = np.floor((lon + 180.0) / 360.0 * n)
    valid = (
        np.isfinite(frow) & np.isfinite(fcol)
        & (frow >= 0) & (frow < n) & (fcol >= 0) & (fcol < n)
    )
    row = np.where(valid, frow, 0).astype(np.int64)
    col = np.where(valid, fcol, 0).astype(np.int64)
    return row, col, valid


def tile_center_latlon(row, column, zoom, dtype=None):
    """Center (lat, lon) of tiles, as the reference computes it.

    The reference's tile center is the *arithmetic mean of the edge
    latitudes* (reference tile.py:45-52), not the inverse projection of
    the Mercator-y midpoint; reproduced here because the cascade re-bins
    tile centers (reference heatmap.py:60-61).
    """
    lat_n = latitude_from_row(row, zoom, dtype)
    r = _as_float(row, dtype)
    lat_s = latitude_from_row(r + 1.0, zoom, dtype)
    lon_w = longitude_from_column(column, zoom, dtype)
    c = _as_float(column, dtype)
    lon_e = longitude_from_column(c + 1.0, zoom, dtype)
    return (lat_n + lat_s) / 2.0, (lon_e + lon_w) / 2.0
