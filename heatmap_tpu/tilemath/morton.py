"""Morton (Z-order) codes for tile keys.

Why Morton codes: the pyramid parent of a Morton code is ``code >> 2``,
and the right shift *preserves sort order*. So one device-side sort of
detail-zoom codes serves every level of the rollup — each coarser level
is a segment-sum over already-sorted keys. This replaces the reference's
per-level reduceByKey/groupByKey shuffle pair (reference
heatmap.py:109-117; 32 shuffles per run, SURVEY.md §3.3) with zero
re-sorts and zero re-projections.

Two widths:
- int32 codes hold zooms <= 15 (2x15 = 30 bits) — the fast TPU path and
  enough for the z0-z15 north-star pyramid (BASELINE.md).
- int64 codes hold zooms <= 29 — covers the reference's z21 detail grid
  (reference heatmap.py:27); requires x64.
"""

from __future__ import annotations

import jax.numpy as jnp


def _part1by1_32(x):
    """Spread the low 16 bits of int32 x into the even bit positions."""
    x = x & 0x0000FFFF
    x = (x | (x << 8)) & 0x00FF00FF
    x = (x | (x << 4)) & 0x0F0F0F0F
    x = (x | (x << 2)) & 0x33333333
    x = (x | (x << 1)) & 0x55555555
    return x


def _compact1by1_32(x):
    """Inverse of :func:`_part1by1_32`."""
    x = x & 0x55555555
    x = (x | (x >> 1)) & 0x33333333
    x = (x | (x >> 2)) & 0x0F0F0F0F
    x = (x | (x >> 4)) & 0x00FF00FF
    x = (x | (x >> 8)) & 0x0000FFFF
    return x


def _part1by1_64(x):
    """Spread the low 32 bits of int64 x into the even bit positions."""
    x = x & 0x00000000FFFFFFFF
    x = (x | (x << 16)) & 0x0000FFFF0000FFFF
    x = (x | (x << 8)) & 0x00FF00FF00FF00FF
    x = (x | (x << 4)) & 0x0F0F0F0F0F0F0F0F
    x = (x | (x << 2)) & 0x3333333333333333
    x = (x | (x << 1)) & 0x5555555555555555
    return x


def _compact1by1_64(x):
    """Inverse of :func:`_part1by1_64`."""
    x = x & 0x5555555555555555
    x = (x | (x >> 1)) & 0x3333333333333333
    x = (x | (x >> 2)) & 0x0F0F0F0F0F0F0F0F
    x = (x | (x >> 4)) & 0x00FF00FF00FF00FF
    x = (x | (x >> 8)) & 0x0000FFFF0000FFFF
    x = (x | (x >> 16)) & 0x00000000FFFFFFFF
    return x


def morton_encode(row, col, dtype=jnp.int32, zoom=None):
    """Interleave (row, col) into a Z-order code; row occupies odd bits.

    ``dtype=jnp.int32`` supports zooms <= 15; ``jnp.int64`` (x64 only)
    supports zooms <= 29. Coordinates beyond the dtype's range would be
    silently bit-truncated into aliased codes, so pass the static
    ``zoom`` whenever it is known to get a loud error instead.
    """
    if zoom is not None:
        limit = 15 if jnp.dtype(dtype).itemsize == 4 else 29
        if zoom > limit:
            raise ValueError(
                f"morton {jnp.dtype(dtype).name} codes hold zooms <= {limit}, "
                f"got zoom={zoom}; use a wider dtype"
            )
    if jnp.dtype(dtype).itemsize == 4:
        r = jnp.asarray(row, jnp.int32)
        c = jnp.asarray(col, jnp.int32)
        return (_part1by1_32(r) << 1) | _part1by1_32(c)
    import jax

    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "morton int64 codes need x64 (jax.config.update"
            "('jax_enable_x64', True)); without it the request would "
            "silently downgrade to int32 and fail on the 64-bit masks"
        )
    r = jnp.asarray(row, jnp.int64)
    c = jnp.asarray(col, jnp.int64)
    return (_part1by1_64(r) << 1) | _part1by1_64(c)


def morton_decode(code):
    """Z-order code -> (row, col), dtype-matched to the code."""
    code = jnp.asarray(code)
    if code.dtype.itemsize == 4:
        return _compact1by1_32(code >> 1), _compact1by1_32(code)
    return (
        _compact1by1_64(code >> 1).astype(jnp.int64),
        _compact1by1_64(code).astype(jnp.int64),
    )


def morton_parent(code, levels=1):
    """The ancestor code ``levels`` zooms coarser: a right shift by 2*levels.

    Order-preserving — sorted codes stay sorted after this, which is the
    whole point (module docstring).
    """
    return code >> (2 * levels)


# ---------------------------------------------------------------------------
# Host-side numpy variants (single source of truth for host pipelines —
# pipeline/batch.py encodes with these, pipeline/cascade.py decodes).
# ---------------------------------------------------------------------------

import numpy as np  # noqa: E402


def morton_encode_np(row, col) -> np.ndarray:
    """Numpy 64-bit Morton encode (zooms <= 29, like the jnp int64 path)."""

    def part(x):
        x = np.asarray(x, np.uint64) & np.uint64(0xFFFFFFFF)
        x = (x | (x << np.uint64(16))) & np.uint64(0x0000FFFF0000FFFF)
        x = (x | (x << np.uint64(8))) & np.uint64(0x00FF00FF00FF00FF)
        x = (x | (x << np.uint64(4))) & np.uint64(0x0F0F0F0F0F0F0F0F)
        x = (x | (x << np.uint64(2))) & np.uint64(0x3333333333333333)
        x = (x | (x << np.uint64(1))) & np.uint64(0x5555555555555555)
        return x

    return ((part(row) << np.uint64(1)) | part(col)).astype(np.int64)


def morton_decode_np(code) -> tuple[np.ndarray, np.ndarray]:
    """Numpy 64-bit Morton decode -> (row, col) int32.

    int32 is always sufficient: a 64-bit Morton code interleaves at
    most 31 bits per axis (2*zoom <= 62), so row/col < 2^31. Halving
    the row/col width matters at egress scale (tens of millions of
    aggregates per job flow through these columns and their coarse
    shifted copies).
    """
    code = np.asarray(code, np.uint64)
    if code.ndim == 1 and code.size > 100_000:
        # Threaded C decode for bulk egress arrays (code_bits=0 makes
        # hm_decode_keys a plain Morton de-interleave). Lazy import:
        # native -> pipeline -> tilemath would cycle at module level.
        from heatmap_tpu import native as _native

        if _native.decode_keys is not None:
            _, _, row, col = _native.decode_keys(
                code.astype(np.int64, copy=False), 0, morton_only=True
            )
            return row, col
    return _morton_decode_np_pure(code)


def morton_range_shards_np(splits, codes) -> np.ndarray:
    """Shard index per detail code under sorted split codes.

    A code belongs to shard ``k`` iff exactly ``k`` splits are <= it
    (``searchsorted(side="right")``), i.e. a split code itself opens the
    range to its right. This is THE ownership convention: the planner,
    the host router, and the range-sharded kernel must all agree on it
    or boundary tiles get double-counted.
    """
    return np.searchsorted(
        np.asarray(splits, np.int64), np.asarray(codes, np.int64),
        side="right").astype(np.int32)


def split_boundary_codes_np(splits, levels: int) -> np.ndarray:
    """Ancestor codes ``levels`` zooms coarser whose tile straddles a split.

    A tile at ``levels`` above detail covers the contiguous detail range
    ``[c << 2L, (c+1) << 2L)``; a split ``s`` falls strictly inside it
    iff ``s >> 2L == c`` and ``s`` is not aligned to the tile's start
    (``s % 4^L != 0``). At ``levels == 0`` no integer split can be
    strictly inside a single-code range, so the set is empty — the
    detail level never needs a cross-shard merge.
    """
    s = np.unique(np.asarray(splits, np.int64))
    if levels <= 0 or s.size == 0:
        return np.empty(0, np.int64)
    block = np.int64(1) << np.int64(2 * levels)
    inner = s[(s % block) != 0]
    return np.unique(inner >> np.int64(2 * levels))


def _morton_decode_np_pure(code) -> tuple[np.ndarray, np.ndarray]:
    """The numpy-only decode: fallback and oracle for the native path."""
    code = np.asarray(code, np.uint64)

    def compact(x):
        x &= np.uint64(0x5555555555555555)
        x = (x | (x >> np.uint64(1))) & np.uint64(0x3333333333333333)
        x = (x | (x >> np.uint64(2))) & np.uint64(0x0F0F0F0F0F0F0F0F)
        x = (x | (x >> np.uint64(4))) & np.uint64(0x00FF00FF00FF00FF)
        x = (x | (x >> np.uint64(8))) & np.uint64(0x0000FFFF0000FFFF)
        x = (x | (x >> np.uint64(16))) & np.uint64(0x00000000FFFFFFFF)
        return x

    return (
        compact(code >> np.uint64(1)).astype(np.int32),
        compact(code).astype(np.int32),
    )
