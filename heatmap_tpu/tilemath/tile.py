"""``Tile`` — scalar host-side compatibility class.

API-parity surface for consumers of the reference's ``Tile`` class
(reference tile.py:3-98): same classmethods, instance methods, and
attribute names, including the public-but-unused ones
(``decode_tile_id``, ``tile_ids_for_all_zoom_levels``; SURVEY.md §8.11).

This is an egress/interop convenience only — device code uses the
vectorized ``tilemath`` functions and integer keys, never this class.
Scalar math uses CPython floats (platform libm doubles), so ids agree
with the reference bit-for-bit.
"""

from __future__ import annotations

import math


def _row_from_latitude(latitude: float, zoom: int) -> float:
    # Same operation order as reference tile.py:17 (bit-identity contract).
    phi = latitude * math.pi / 180
    return math.floor(
        (1 - math.log(math.tan(phi) + 1 / math.cos(phi)) / math.pi) / 2 * (1 << zoom)
    )


def _column_from_longitude(longitude: float, zoom: int) -> float:
    return math.floor((longitude + 180.0) / 360.0 * (1 << zoom))


def _latitude_from_row(row: float, zoom: int) -> float:
    n = math.pi - 2.0 * math.pi * row / (1 << zoom)
    return 180.0 / math.pi * math.atan(0.5 * (math.exp(n) - math.exp(-n)))


def _longitude_from_column(column: float, zoom: int) -> float:
    return float(column) / (1 << zoom) * 360.0 - 180.0


class Tile:
    """Web-Mercator map tile with reference-compatible geometry accessors."""

    MAX_ZOOM = 16
    MIN_ZOOM = 0

    tile_id: str
    zoom: int
    row: int
    column: int
    latitude_north: float
    latitude_south: float
    longitude_west: float
    longitude_east: float
    center_latitude: float
    center_longitude: float

    # -- projection classmethods (reference tile.py:8-30) ------------------

    @classmethod
    def row_from_latitude(cls, latitude, zoom):
        return _row_from_latitude(latitude, zoom)

    @classmethod
    def column_from_longitude(cls, longitude, zoom):
        return _column_from_longitude(longitude, zoom)

    @classmethod
    def latitude_from_row(cls, row, zoom):
        return _latitude_from_row(row, zoom)

    @classmethod
    def longitude_from_column(cls, column, zoom):
        return _longitude_from_column(column, zoom)

    @classmethod
    def tile_id_from_lat_long(cls, latitude, longitude, zoom):
        row = int(_row_from_latitude(latitude, zoom))
        column = int(_column_from_longitude(longitude, zoom))
        return cls.tile_id_from_row_column(row, column, zoom)

    @classmethod
    def tile_id_from_row_column(cls, row, column, zoom):
        return f"{zoom}_{row}_{column}"

    # -- constructors / codecs (reference tile.py:32-77) -------------------

    @classmethod
    def tile_from_tile_id(cls, tile_id):
        # Parity note: only a wrong part-count returns None (reference
        # tile.py:35-36); 3 non-numeric parts raise ValueError exactly as
        # the reference's unguarded int() does. keys.parse_tile_id is the
        # lenient variant that returns None for both.
        parts = tile_id.split("_")
        if len(parts) != 3:
            return None

        tile = cls()
        tile.tile_id = tile_id
        tile.zoom = int(parts[0])
        tile.row = int(parts[1])
        tile.column = int(parts[2])
        tile.latitude_north = _latitude_from_row(tile.row, tile.zoom)
        tile.latitude_south = _latitude_from_row(tile.row + 1, tile.zoom)
        tile.longitude_west = _longitude_from_column(tile.column, tile.zoom)
        tile.longitude_east = _longitude_from_column(tile.column + 1, tile.zoom)
        # Arithmetic-mean center, NOT the Mercator midpoint (reference
        # tile.py:51-52) — the cascade's re-binning depends on this.
        tile.center_latitude = (tile.latitude_north + tile.latitude_south) / 2.0
        tile.center_longitude = (tile.longitude_east + tile.longitude_west) / 2.0
        return tile

    @classmethod
    def decode_tile_id(cls, tile_id):
        parts = tile_id.split("_")
        if len(parts) != 3:
            return None
        return {
            "id": tile_id,
            "zoom": int(parts[0]),
            "row": int(parts[1]),
            "column": int(parts[2]),
        }

    @classmethod
    def tile_ids_for_all_zoom_levels(cls, tile_id):
        # Note: range excludes MIN_ZOOM, i.e. zooms 16..1 — preserved quirk
        # (reference tile.py:83, SURVEY.md §8.11).
        tile = cls.tile_from_tile_id(tile_id)
        return [
            cls.tile_id_from_lat_long(tile.center_latitude, tile.center_longitude, z)
            for z in range(cls.MAX_ZOOM, cls.MIN_ZOOM, -1)
        ]

    # -- pyramid navigation (reference tile.py:60-64,88-98) ----------------

    def parent_id(self):
        return Tile.tile_id_from_lat_long(
            self.center_latitude, self.center_longitude, self.zoom - 1
        )

    def parent(self):
        return Tile.tile_from_tile_id(self.parent_id())

    def children(self):
        lat_mid_n = (self.center_latitude + self.latitude_north) / 2
        lat_mid_s = (self.center_latitude + self.latitude_south) / 2
        lon_mid_e = (self.center_longitude + self.longitude_east) / 2
        lon_mid_w = (self.center_longitude + self.longitude_west) / 2
        z = self.zoom + 1
        return [
            Tile.tile_id_from_lat_long(lat_mid_n, lon_mid_e, z),
            Tile.tile_id_from_lat_long(lat_mid_n, lon_mid_w, z),
            Tile.tile_id_from_lat_long(lat_mid_s, lon_mid_e, z),
            Tile.tile_id_from_lat_long(lat_mid_s, lon_mid_w, z),
        ]

    def __repr__(self):
        return f"Tile({self.tile_id!r})"
