"""Tile geometry core: vectorized Web-Mercator math and integer tile keys.

Semantics contract with the reference (reference tile.py:8-30):
floor-based binning, no pole clamping, no antimeridian wraparound.
"""

from heatmap_tpu.tilemath.mercator import (  # noqa: F401
    MAX_LATITUDE,
    column_from_longitude,
    latitude_from_row,
    longitude_from_column,
    mercator_x,
    mercator_y,
    project_points,
    row_from_latitude,
)
from heatmap_tpu.tilemath.keys import (  # noqa: F401
    children_rowcol,
    pack_key,
    parent_rowcol,
    parse_tile_id,
    rowcol_at_zoom,
    tile_id_from_lat_long,
    tile_id_string,
    unpack_key,
)
from heatmap_tpu.tilemath.morton import (  # noqa: F401
    morton_decode,
    morton_encode,
    morton_parent,
    morton_range_shards_np,
    split_boundary_codes_np,
)
from heatmap_tpu.tilemath.tile import Tile  # noqa: F401
