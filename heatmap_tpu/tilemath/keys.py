"""Integer tile keys and the string-id compatibility codec.

The reference addresses tiles with ``"zoom_row_col"`` strings built and
re-parsed in every mapper (reference tile.py:32-58) and coarsens tiles by
round-tripping centers through inverse+forward projection per level
(reference tile.py:60-64, heatmap.py:60-61). On TPU, tiles are integers:

- ``(row, col)`` int32 pairs at a given zoom (rows/cols fit int32 for all
  zoom <= 30);
- a packed int64 ``pack_key(zoom, row, col)`` when a single sortable
  scalar is needed (requires x64);
- Morton codes (see morton.py) when pyramid-order locality is needed.

Parent/child navigation is pure bit arithmetic — ``parent = (r>>1, c>>1)``
— which is mathematically identical to the reference's center
re-projection for in-range tiles (proved + property-tested in
tests/test_keys.py): the tile center is strictly inside the tile, so
re-binning it one zoom coarser always lands on the half-resolution tile.

Strings appear only at the egress boundary for compatibility.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Packed-key layout: | zoom:6 | row:29 | col:29 | — zooms 0..29 lossless
# (rows/cols at zoom z need z bits; z30 would need 30-bit fields).
_ROW_BITS = 29
_COL_BITS = 29
MAX_PACK_ZOOM = 29


def pack_key(zoom, row, col):
    """Pack (zoom, row, col) into a sortable int64 scalar key.

    Sort order is (zoom, row, col) lexicographic. Requires x64: without
    it the int64 request silently downgrades to int32 and the shifts
    wrap, so refuse loudly instead.
    """
    import jax

    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "pack_key needs int64 keys; enable x64 (jax.config.update"
            "('jax_enable_x64', True)) or use Morton int32 codes for zoom<=15"
        )
    try:  # loud zoom-range check when zoom is concrete (host values)
        if int(np.max(np.asarray(zoom))) > MAX_PACK_ZOOM:
            raise ValueError(
                f"pack_key fields hold zooms <= {MAX_PACK_ZOOM}; got {zoom}"
            )
    except (TypeError, jax.errors.ConcretizationTypeError):
        pass  # traced zoom: caller is responsible for the range
    z = jnp.asarray(zoom, jnp.int64)
    r = jnp.asarray(row, jnp.int64)
    c = jnp.asarray(col, jnp.int64)
    return (z << (_ROW_BITS + _COL_BITS)) | (r << _COL_BITS) | c


def unpack_key(key):
    """Inverse of :func:`pack_key` -> (zoom, row, col) int32."""
    k = jnp.asarray(key, jnp.int64)
    col = (k & ((1 << _COL_BITS) - 1)).astype(jnp.int32)
    row = ((k >> _COL_BITS) & ((1 << _ROW_BITS) - 1)).astype(jnp.int32)
    zoom = (k >> (_ROW_BITS + _COL_BITS)).astype(jnp.int32)
    return zoom, row, col


def parent_rowcol(row, col):
    """Tile at zoom-1 containing (row, col): a right shift.

    Equivalent to the reference's center re-projection (reference
    tile.py:60-61) for in-range tiles; see module docstring.
    """
    return row >> 1, col >> 1


def rowcol_at_zoom(row, col, from_zoom, to_zoom):
    """Re-bin a tile's (row, col) from ``from_zoom`` to a coarser ``to_zoom``."""
    if to_zoom > from_zoom:
        raise ValueError(
            f"rowcol_at_zoom only coarsens: from_zoom={from_zoom} -> to_zoom={to_zoom}"
        )
    shift = from_zoom - to_zoom
    return row >> shift, col >> shift


def children_rowcol(row, col):
    """The four zoom+1 children of (row, col) as ((r,c) x 4).

    Matches the set produced by the reference's quadrant-midpoint
    re-binning (reference tile.py:88-98).
    """
    r2, c2 = row * 2, col * 2
    return ((r2, c2), (r2, c2 + 1), (r2 + 1, c2), (r2 + 1, c2 + 1))


# ---------------------------------------------------------------------------
# Host-side string codec (egress-boundary compatibility with the reference's
# "zoom_row_col" ids, reference tile.py:56-58).
# ---------------------------------------------------------------------------


def tile_id_string(zoom, row, col) -> str:
    """Reference-format tile id string (reference tile.py:56-58)."""
    return f"{int(zoom)}_{int(row)}_{int(col)}"


def parse_tile_id(tile_id: str):
    """Parse ``"zoom_row_col"`` -> (zoom, row, col) or None if malformed.

    None-on-malformed mirrors reference tile.py:33-36.
    """
    parts = tile_id.split("_")
    if len(parts) != 3:
        return None
    try:
        return int(parts[0]), int(parts[1]), int(parts[2])
    except ValueError:
        return None


def tile_id_from_lat_long(latitude, longitude, zoom) -> str:
    """Scalar host-side convenience mirroring reference tile.py:8-13.

    Delegates to the single scalar-projection implementation in
    tilemath.tile (CPython platform-libm doubles, so results agree with
    the reference bit-for-bit).
    """
    from heatmap_tpu.tilemath import tile as _tile

    row = int(_tile._row_from_latitude(latitude, zoom))
    col = int(_tile._column_from_longitude(longitude, zoom))
    return tile_id_string(zoom, row, col)


def tile_ids_to_arrays(tile_ids):
    """Vectorize a sequence of string ids -> (zoom, row, col) int32 numpy arrays.

    Malformed ids are dropped (reference returns None for them,
    reference tile.py:35-36); returns the keep-mask as the 4th element.
    """
    zooms, rows, cols, keep = [], [], [], []
    for tid in tile_ids:
        parsed = parse_tile_id(tid)
        if parsed is None:
            keep.append(False)
            continue
        keep.append(True)
        z, r, c = parsed
        zooms.append(z)
        rows.append(r)
        cols.append(c)
    return (
        np.asarray(zooms, np.int32),
        np.asarray(rows, np.int32),
        np.asarray(cols, np.int32),
        np.asarray(keep, bool),
    )
