# Driver image for the TPU-native heatmap job — the analog of the
# reference's Spark driver image (reference Dockerfile:1-7, which
# copies heatmap.py/tile.py + the Cassandra connector JAR into a
# kubespark base). Here the base is a JAX TPU image and the payload is
# the heatmap_tpu package; no connector JAR (storage IO is host-side
# Python in heatmap_tpu.io).
FROM python:3.11-slim

# Toolchain for the native runtime (C++ point codec + staging pool).
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

# JAX with TPU support; pinned by the deployment, not the framework.
RUN pip install --no-cache-dir "jax[tpu]" -f \
    https://storage.googleapis.com/jax-releases/libtpu_releases.html

WORKDIR /opt/heatmap
COPY native ./native
RUN make -C native
COPY heatmap_tpu ./heatmap_tpu
COPY tools ./tools
COPY submit-heatmap bench.py ./
ENV PYTHONPATH=/opt/heatmap
ENTRYPOINT ["./submit-heatmap"]
