// Host staging-buffer pool: fixed set of page-aligned buffers recycled
// across micro-batches.
//
// Role: the memory-management piece of the runtime the reference left to
// Spark (executor memory + spill to spark.local.dir, reference
// submit-heatmap:14). Host->device feeds stage point columns here so the
// ingest pipeline reuses a bounded set of aligned allocations instead of
// malloc/free per batch — acquire blocks when all buffers are in flight,
// which back-pressures the decoder thread against device compute.
//
// Plain C ABI for ctypes; buffers are page-aligned (4096) so DMA-friendly
// copies and madvise tricks stay available to the transfer layer.

#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <mutex>
#include <vector>

namespace {

struct Pool {
  std::vector<void*> bufs;
  std::vector<int> free_ids;
  int64_t buf_bytes;
  std::mutex mu;
  std::condition_variable cv;

  ~Pool() {
    for (void* b : bufs) std::free(b);
  }
};

}  // namespace

extern "C" {

void* hm_pool_create(int64_t buf_bytes, int n_bufs) {
  if (buf_bytes <= 0 || n_bufs <= 0) return nullptr;
  auto* p = new Pool();
  p->buf_bytes = buf_bytes;
  // Round up to the 4096 alignment aligned_alloc requires of the size.
  int64_t size = (buf_bytes + 4095) / 4096 * 4096;
  for (int i = 0; i < n_bufs; ++i) {
    void* b = std::aligned_alloc(4096, size);
    if (!b) {
      delete p;
      return nullptr;
    }
    p->bufs.push_back(b);
    p->free_ids.push_back(i);
  }
  return p;
}

// Block until a buffer is free; returns its id (the caller maps ids to
// base pointers once via hm_pool_buffer).
int hm_pool_acquire(void* handle) {
  auto* p = static_cast<Pool*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv.wait(lk, [&] { return !p->free_ids.empty(); });
  int id = p->free_ids.back();
  p->free_ids.pop_back();
  return id;
}

// Non-blocking acquire: -1 if every buffer is in flight.
int hm_pool_try_acquire(void* handle) {
  auto* p = static_cast<Pool*>(handle);
  std::lock_guard<std::mutex> lk(p->mu);
  if (p->free_ids.empty()) return -1;
  int id = p->free_ids.back();
  p->free_ids.pop_back();
  return id;
}

void hm_pool_release(void* handle, int id) {
  auto* p = static_cast<Pool*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->free_ids.push_back(id);
  }
  p->cv.notify_one();
}

void* hm_pool_buffer(void* handle, int id) {
  auto* p = static_cast<Pool*>(handle);
  if (id < 0 || static_cast<size_t>(id) >= p->bufs.size()) return nullptr;
  return p->bufs[id];
}

int64_t hm_pool_buf_bytes(void* handle) {
  return static_cast<Pool*>(handle)->buf_bytes;
}

int hm_pool_size(void* handle) {
  return static_cast<int>(static_cast<Pool*>(handle)->bufs.size());
}

void hm_pool_destroy(void* handle) { delete static_cast<Pool*>(handle); }

}  // extern "C"
