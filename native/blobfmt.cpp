// Native JSON blob-body formatter: the egress hot loop.
//
// The reference-format egress must turn ~tens of millions of
// (row, col, value) aggregates into per-blob JSON documents
// '{"z_r_c": v, ...}'. numpy's per-aggregate number->string formatting
// is the measured floor of that path (~0.5 M aggregates/s,
// PERF_NOTES.md round 2, GIL-bound so threads don't help Python).
// This formatter does the same work in C with integer formatting and
// OS threads: the Python side passes the (already sorted) level
// columns plus the blob-start mask, and receives ONE buffer of
// NUL-separated '{...}' documents in order — the exact contract of the
// numpy join/split trick it replaces (pipeline/cascade.py
// json_blobs_from_level_arrays).
//
// Scope: values must be integral doubles with |v| < 1e15 (cascade
// counts always are — weights never reach blob egress). The Python
// caller verifies that precondition and falls back to the numpy path
// otherwise, so float-repr parity questions never arise here:
// "%lld.0" is exactly repr(float(k)) == json.dumps(float(k)) for
// integral doubles below 1e16.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Max chars one aggregate can contribute:
//   sep (3: '}\0{' or ', ') + '"' + zoom(2) + '_' + row(12) + '_' +
//   col(12) + '": ' + digits(16) + '.0'  => < 56. Use 64.
constexpr int64_t kMaxPer = 64;

inline char* put_i64(char* p, long long v) {
  if (v < 0) {  // not produced by tile math, but stay correct
    *p++ = '-';
    v = -v;
  }
  char tmp[24];
  int n = 0;
  do {
    tmp[n++] = static_cast<char>('0' + (v % 10));
    v /= 10;
  } while (v);
  while (n) *p++ = tmp[--n];
  return p;
}

struct Slice {
  int64_t lo, hi;  // aggregate range, lo aligned to a blob start
  char* buf = nullptr;
  int64_t len = 0;
};

void format_slice(const int64_t* rows, const int64_t* cols,
                  const double* vals, const uint8_t* is_start,
                  int32_t zoom, bool first_slice, Slice* s) {
  const int64_t n = s->hi - s->lo;
  s->buf = static_cast<char*>(std::malloc(static_cast<size_t>(n) * kMaxPer));
  if (s->buf == nullptr) {
    s->len = -1;
    return;
  }
  char* p = s->buf;
  char zbuf[8];
  char* zend = put_i64(zbuf, zoom);
  const int zlen = static_cast<int>(zend - zbuf);
  for (int64_t i = s->lo; i < s->hi; ++i) {
    if (is_start[i]) {
      if (i == s->lo && first_slice) {
        *p++ = '{';
      } else {
        *p++ = '}';
        *p++ = '\0';
        *p++ = '{';
      }
    } else {
      *p++ = ',';
      *p++ = ' ';
    }
    *p++ = '"';
    std::memcpy(p, zbuf, zlen);
    p += zlen;
    *p++ = '_';
    p = put_i64(p, rows[i]);
    *p++ = '_';
    p = put_i64(p, cols[i]);
    *p++ = '"';
    *p++ = ':';
    *p++ = ' ';
    p = put_i64(p, static_cast<long long>(vals[i]));
    *p++ = '.';
    *p++ = '0';
  }
  s->len = p - s->buf;
}

}  // namespace

extern "C" {

// Format NUL-separated '{...}' blob documents for one (sorted) level.
// rows/cols: int64[n]; vals: double[n] (integral, |v| < 1e15 —
// caller-checked); is_start: uint8[n] with is_start[0] == 1.
// On success returns the byte length and stores a malloc'd buffer in
// *out (free with hm_blobfmt_free); returns -1 on allocation failure,
// 0 with *out = nullptr for n == 0.
int64_t hm_format_blob_bodies(const int64_t* rows, const int64_t* cols,
                              const double* vals, const uint8_t* is_start,
                              int64_t n, int32_t zoom, int32_t n_threads,
                              char** out) {
  *out = nullptr;
  if (n <= 0) return 0;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > 16) n_threads = 16;

  // Slice boundaries aligned to blob starts so every document is
  // formatted by exactly one thread.
  std::vector<Slice> slices;
  int64_t lo = 0;
  for (int t = 1; t < n_threads && lo < n; ++t) {
    int64_t target = (n * t) / n_threads;
    while (target < n && !is_start[target]) ++target;
    if (target > lo && target < n) {
      slices.push_back({lo, target});
      lo = target;
    }
  }
  slices.push_back({lo, n});

  std::vector<std::thread> workers;
  for (size_t k = 0; k < slices.size(); ++k) {
    workers.emplace_back(format_slice, rows, cols, vals, is_start, zoom,
                         k == 0, &slices[k]);
  }
  for (auto& w : workers) w.join();

  int64_t total = 1;  // trailing '}'
  bool failed = false;
  for (auto& s : slices) {
    if (s.len < 0) failed = true;
    total += s.len;
  }
  char* merged = failed ? nullptr
                        : static_cast<char*>(std::malloc(total));
  int64_t off = 0;
  for (auto& s : slices) {
    if (merged != nullptr && s.len > 0) {
      std::memcpy(merged + off, s.buf, s.len);
      off += s.len;
    }
    std::free(s.buf);
  }
  if (merged == nullptr) return -1;
  merged[off++] = '}';
  *out = merged;
  return off;
}

void hm_blobfmt_free(char* buf) { std::free(buf); }

}  // extern "C"
