// Native JSON blob-body formatter: the egress hot loop.
//
// The reference-format egress must turn ~tens of millions of
// (row, col, value) aggregates into per-blob JSON documents
// '{"z_r_c": v, ...}'. numpy's per-aggregate number->string formatting
// is the measured floor of that path (~0.5 M aggregates/s,
// PERF_NOTES.md round 2, GIL-bound so threads don't help Python).
// This formatter does the same work in C with integer formatting and
// OS threads: the Python side passes the (already sorted) level
// columns plus the blob-start mask, and receives ONE buffer of
// NUL-separated '{...}' documents in order — the exact contract of the
// numpy join/split trick it replaces (pipeline/cascade.py
// json_blobs_from_level_arrays).
//
// Scope: values must be integral doubles with |v| < 1e15 (cascade
// counts always are — weights never reach blob egress). The Python
// caller verifies that precondition and falls back to the numpy path
// otherwise, so float-repr parity questions never arise here:
// "%lld.0" is exactly repr(float(k)) == json.dumps(float(k)) for
// integral doubles below 1e16.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Max chars one aggregate can contribute:
//   sep (3: '}\0{' or ', ') + '"' + zoom(2) + '_' + row(12) + '_' +
//   col(12) + '": ' + digits(16) + '.0'  => < 56. Use 64.
constexpr int64_t kMaxPer = 64;

inline char* put_i64(char* p, long long v) {
  if (v < 0) {  // not produced by tile math, but stay correct
    *p++ = '-';
    v = -v;
  }
  char tmp[24];
  int n = 0;
  do {
    tmp[n++] = static_cast<char>('0' + (v % 10));
    v /= 10;
  } while (v);
  while (n) *p++ = tmp[--n];
  return p;
}

struct Slice {
  int64_t lo, hi;  // aggregate range, lo aligned to a blob start
  char* buf = nullptr;
  int64_t len = 0;
};

// Concatenate per-slice buffers (freeing them) into one malloc'd
// result, with `tail` extra bytes reserved past the payload. Returns
// the payload length written so far; a failed slice's negative len is
// propagated as-is, and -1 is returned if the final allocation fails
// (slices are always freed either way).
int64_t merge_slices(std::vector<Slice>& slices, int64_t tail, char** out) {
  int64_t total = tail;
  int64_t failed = 0;
  for (auto& s : slices) {
    if (s.len < 0 && failed == 0) failed = s.len;
    if (s.len > 0) total += s.len;
  }
  char* merged =
      failed ? nullptr : static_cast<char*>(std::malloc(total ? total : 1));
  int64_t off = 0;
  for (auto& s : slices) {
    if (merged != nullptr && s.len > 0) {
      std::memcpy(merged + off, s.buf, s.len);
      off += s.len;
    }
    std::free(s.buf);
  }
  if (failed) return failed;
  if (merged == nullptr) return -1;
  *out = merged;
  return off;
}

void format_slice(const int64_t* rows, const int64_t* cols,
                  const double* vals, const uint8_t* is_start,
                  int32_t zoom, bool first_slice, Slice* s) {
  const int64_t n = s->hi - s->lo;
  s->buf = static_cast<char*>(std::malloc(static_cast<size_t>(n) * kMaxPer));
  if (s->buf == nullptr) {
    s->len = -1;
    return;
  }
  char* p = s->buf;
  char zbuf[8];
  char* zend = put_i64(zbuf, zoom);
  const int zlen = static_cast<int>(zend - zbuf);
  for (int64_t i = s->lo; i < s->hi; ++i) {
    if (is_start[i]) {
      if (i == s->lo && first_slice) {
        *p++ = '{';
      } else {
        *p++ = '}';
        *p++ = '\0';
        *p++ = '{';
      }
    } else {
      *p++ = ',';
      *p++ = ' ';
    }
    *p++ = '"';
    std::memcpy(p, zbuf, zlen);
    p += zlen;
    *p++ = '_';
    p = put_i64(p, rows[i]);
    *p++ = '_';
    p = put_i64(p, cols[i]);
    *p++ = '"';
    *p++ = ':';
    *p++ = ' ';
    p = put_i64(p, static_cast<long long>(vals[i]));
    *p++ = '.';
    *p++ = '0';
  }
  s->len = p - s->buf;
}

}  // namespace

extern "C" {

// Format NUL-separated '{...}' blob documents for one (sorted) level.
// rows/cols: int64[n]; vals: double[n] (integral, |v| < 1e15 —
// caller-checked); is_start: uint8[n] with is_start[0] == 1.
// On success returns the byte length and stores a malloc'd buffer in
// *out (free with hm_blobfmt_free); returns -1 on allocation failure,
// 0 with *out = nullptr for n == 0.
int64_t hm_format_blob_bodies(const int64_t* rows, const int64_t* cols,
                              const double* vals, const uint8_t* is_start,
                              int64_t n, int32_t zoom, int32_t n_threads,
                              char** out) {
  *out = nullptr;
  if (n <= 0) return 0;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > 16) n_threads = 16;

  // Slice boundaries aligned to blob starts so every document is
  // formatted by exactly one thread.
  std::vector<Slice> slices;
  int64_t lo = 0;
  for (int t = 1; t < n_threads && lo < n; ++t) {
    int64_t target = (n * t) / n_threads;
    while (target < n && !is_start[target]) ++target;
    if (target > lo && target < n) {
      slices.push_back({lo, target});
      lo = target;
    }
  }
  slices.push_back({lo, n});

  std::vector<std::thread> workers;
  for (size_t k = 0; k < slices.size(); ++k) {
    workers.emplace_back(format_slice, rows, cols, vals, is_start, zoom,
                         k == 0, &slices[k]);
  }
  for (auto& w : workers) w.join();

  int64_t off = merge_slices(slices, /*tail=*/1, out);
  if (off < 0) return -1;
  (*out)[off++] = '}';  // trailing close of the last document
  return off;
}

// Format NUL-separated blob id strings "user|timespan|z_r_c" for one
// level's blob-run starts. user_idx/ts_idx: int32[n] dictionary
// indices; coarse_row/coarse_col: int32[n]; the name tables arrive as
// one UTF-8 buffer each with n_* offsets[i]..offsets[i+1] spans
// (offsets arrays have n_*+1 entries). Returns the byte length with a
// malloc'd buffer in *out (free with hm_blobfmt_free), 0 for n == 0,
// or a distinct negative code: -1 allocation failure, -2 dictionary
// index out of range, -3 coarse_zoom out of [0, 999].
int64_t hm_format_blob_ids(const int32_t* user_idx, const int32_t* ts_idx,
                           const int32_t* coarse_row,
                           const int32_t* coarse_col, int64_t n,
                           int32_t coarse_zoom, const char* user_buf,
                           const int64_t* user_offs, int32_t n_users,
                           const char* ts_buf, const int64_t* ts_offs,
                           int32_t n_ts, int32_t n_threads, char** out) {
  *out = nullptr;
  if (n <= 0) return 0;
  // Tile zooms are tiny non-negatives (<= 31 in practice); the 3-digit
  // budget in `per` and the zbuf below depend on this bound.
  if (coarse_zoom < 0 || coarse_zoom > 999) return -3;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > 16) n_threads = 16;

  int64_t max_user = 0, max_ts = 0;
  for (int32_t i = 0; i < n_users; ++i) {
    const int64_t l = user_offs[i + 1] - user_offs[i];
    if (l > max_user) max_user = l;
  }
  for (int32_t i = 0; i < n_ts; ++i) {
    const int64_t l = ts_offs[i + 1] - ts_offs[i];
    if (l > max_ts) max_ts = l;
  }
  // user + '|' + timespan + '|' + zoom(3) + '_' + row(12) + '_' +
  // col(12) + NUL, padded.
  const int64_t per = max_user + max_ts + 34;

  const int64_t kMinPerThread = 1 << 15;
  int64_t want = (n + kMinPerThread - 1) / kMinPerThread;
  if (want < n_threads) n_threads = static_cast<int32_t>(want);
  std::vector<Slice> slices;
  const int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int64_t lo = 0; lo < n; lo += chunk)
    slices.push_back({lo, lo + chunk < n ? lo + chunk : n});

  char zbuf[8];
  char* zend = put_i64(zbuf, coarse_zoom);
  const int zlen = static_cast<int>(zend - zbuf);

  std::vector<std::thread> workers;
  for (auto& s : slices) {
    workers.emplace_back([&, sp = &s] {
      const int64_t m = sp->hi - sp->lo;
      sp->buf = static_cast<char*>(
          std::malloc(static_cast<size_t>(m) * per));
      if (sp->buf == nullptr) {
        sp->len = -1;
        return;
      }
      char* p = sp->buf;
      for (int64_t i = sp->lo; i < sp->hi; ++i) {
        const int32_t u = user_idx[i], t = ts_idx[i];
        if (u < 0 || u >= n_users || t < 0 || t >= n_ts) {
          sp->len = -2;
          std::free(sp->buf);
          sp->buf = nullptr;
          return;
        }
        const int64_t ul = user_offs[u + 1] - user_offs[u];
        std::memcpy(p, user_buf + user_offs[u], ul);
        p += ul;
        *p++ = '|';
        const int64_t tl = ts_offs[t + 1] - ts_offs[t];
        std::memcpy(p, ts_buf + ts_offs[t], tl);
        p += tl;
        *p++ = '|';
        std::memcpy(p, zbuf, zlen);
        p += zlen;
        *p++ = '_';
        p = put_i64(p, coarse_row[i]);
        *p++ = '_';
        p = put_i64(p, coarse_col[i]);
        *p++ = '\0';
      }
      sp->len = p - sp->buf;
    });
  }
  for (auto& w : workers) w.join();

  return merge_slices(slices, /*tail=*/0, out);
}

void hm_blobfmt_free(char* buf) { std::free(buf); }

}  // extern "C"
