// Native cascade-level key decoder: the egress decode hot loop.
//
// After the device cascade, every pyramid level hands back up to tens
// of millions of composite int64 keys ((slot << code_bits) | morton)
// that must be split into slot ids and (row, col) tile coordinates
// before egress (pipeline/cascade.py decode_levels; the reference did
// this per record in Python string parsing, heatmap.py:80-83). The
// numpy path is ~8 full-array passes (shift, mask, 6 Morton compact
// steps x 2 axes) of GIL-bound single-thread work; this does one fused
// pass per element across OS threads into caller-allocated buffers.
//
// code_bits == 0 degrades to a plain Morton decode (slot = key), which
// is how the Python side exposes a threaded morton_decode as well.

#include <cstdint>
#include <thread>
#include <vector>

namespace {

// Compact the even bits of x into the low half (standard Morton
// de-interleave); row = compact(code >> 1), col = compact(code).
inline uint64_t compact_even(uint64_t x) {
  x &= 0x5555555555555555ULL;
  x = (x | (x >> 1)) & 0x3333333333333333ULL;
  x = (x | (x >> 2)) & 0x0F0F0F0F0F0F0F0FULL;
  x = (x | (x >> 4)) & 0x00FF00FF00FF00FFULL;
  x = (x | (x >> 8)) & 0x0000FFFF0000FFFFULL;
  x = (x | (x >> 16)) & 0x00000000FFFFFFFFULL;
  return x;
}

void decode_range(const int64_t* keys, int64_t lo, int64_t hi,
                  int32_t code_bits, int32_t* slot, int64_t* code,
                  int32_t* row, int32_t* col) {
  const uint64_t mask =
      code_bits >= 64 ? ~0ULL : ((1ULL << code_bits) - 1ULL);
  for (int64_t i = lo; i < hi; ++i) {
    const uint64_t k = static_cast<uint64_t>(keys[i]);
    const uint64_t c = code_bits ? (k & mask) : k;
    if (slot != nullptr) slot[i] = static_cast<int32_t>(k >> code_bits);
    if (code != nullptr) code[i] = static_cast<int64_t>(c);
    row[i] = static_cast<int32_t>(compact_even(c >> 1));
    col[i] = static_cast<int32_t>(compact_even(c));
  }
}

}  // namespace

extern "C" {

// Split composite keys into slot/code/row/col columns. Output buffers
// are caller-allocated with n elements; slot and/or code may be null
// to skip those columns (Morton-only decode avoids 12 bytes/element
// of dead stores). Returns 0, or -1 on invalid arguments. Threads
// write disjoint index ranges (no shared mutable state); both the
// full and null-column forms run under the TSAN selftest.
int hm_decode_keys(const int64_t* keys, int64_t n, int32_t code_bits,
                   int32_t* slot, int64_t* code, int32_t* row,
                   int32_t* col, int32_t n_threads) {
  if (n < 0 || code_bits < 0 || code_bits > 63) return -1;
  if (n == 0) return 0;
  if (n_threads < 1) n_threads = 1;
  const int64_t kMinPerThread = 1 << 16;
  int64_t want = (n + kMinPerThread - 1) / kMinPerThread;
  if (want < n_threads) n_threads = static_cast<int32_t>(want);
  if (n_threads <= 1) {
    decode_range(keys, 0, n, code_bits, slot, code, row, col);
    return 0;
  }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  const int64_t per = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    const int64_t lo = t * per;
    const int64_t hi = lo + per < n ? lo + per : n;
    if (lo >= hi) break;
    threads.emplace_back(decode_range, keys, lo, hi, code_bits, slot,
                         code, row, col);
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"
