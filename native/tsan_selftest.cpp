// ThreadSanitizer self-test for the native runtime (race detection —
// the sanitizer coverage the reference never had or needed, since its
// only concurrency lived inside Spark; SURVEY.md §5).
//
// Exercises the two concurrent components end to end under TSAN:
//   1. the multi-worker CSV reader (shared intern table, bounded
//      queue, consumer peek/take) including mid-stream close while
//      workers are still parsing (destructor/stop-flag paths);
//   2. the staging pool hammered from multiple producer/consumer
//      threads (acquire/release under contention).
//
// Build + run: `make -C native tsan` (compiles everything with
// -fsanitize=thread; a detected race makes the binary exit non-zero).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* hm_csv_open(const char* path, int64_t batch_rows, int lat_col,
                  int lon_col, int uid_col, int src_col, int ts_col,
                  int queue_depth, int want_arenas, int n_workers);
int64_t hm_csv_peek(void* handle, int64_t* uid_bytes, int64_t* src_bytes,
                    int64_t* new_names_bytes);
int hm_csv_take(void* handle, double* lat, double* lon, int64_t* ts,
                char* uid_arena, char* src_arena, int32_t* routed,
                uint8_t* background, char* new_names_arena);
void hm_csv_close(void* handle);

void* hm_pool_create(int64_t buf_bytes, int n_bufs);
int hm_pool_acquire(void* handle);
void hm_pool_release(void* handle, int id);
void* hm_pool_buffer(void* handle, int id);
void hm_pool_destroy(void* handle);

int64_t hm_format_blob_bodies(const int64_t* rows, const int64_t* cols,
                              const double* vals, const uint8_t* is_start,
                              int64_t n, int32_t zoom, int32_t n_threads,
                              char** out);
void hm_blobfmt_free(char* buf);

int hm_decode_keys(const int64_t* keys, int64_t n, int32_t code_bits,
                   int32_t* slot, int64_t* code, int32_t* row, int32_t* col,
                   int32_t n_threads);

int64_t hm_format_blob_ids(const int32_t* user_idx, const int32_t* ts_idx,
                           const int32_t* coarse_row,
                           const int32_t* coarse_col, int64_t n,
                           int32_t coarse_zoom, const char* user_buf,
                           const int64_t* user_offs, int32_t n_users,
                           const char* ts_buf, const int64_t* ts_offs,
                           int32_t n_ts, int32_t n_threads, char** out);
}

namespace {

constexpr int kRows = 200000;
constexpr int kUsers = 300;

std::string write_csv() {
  std::string path = "/tmp/hm_tsan_points.csv";
  FILE* f = std::fopen(path.c_str(), "w");
  if (!f) {
    std::fprintf(stderr, "cannot write %s\n", path.c_str());
    std::exit(1);
  }
  std::fprintf(f, "latitude,longitude,user_id,source,timestamp\n");
  for (int i = 0; i < kRows; ++i) {
    const char* src = (i % 11 == 0) ? "background" : "gps";
    int u = i % kUsers;
    if (u % 7 == 0)
      std::fprintf(f, "%.6f,%.6f,x-%d,%s,%d\n", 40.0 + u * 0.01,
                   -120.0 + u * 0.01, u, src, i);
    else if (u % 5 == 0)
      std::fprintf(f, "%.6f,%.6f,rt-%d,%s,%d\n", 40.0 + u * 0.01,
                   -120.0 + u * 0.01, u, src, i);
    else
      std::fprintf(f, "%.6f,%.6f,user-%d,%s,%d\n", 40.0 + u * 0.01,
                   -120.0 + u * 0.01, u, src, i);
  }
  std::fclose(f);
  return path;
}

int64_t drain(const std::string& path, int n_workers, bool early_close) {
  void* r = hm_csv_open(path.c_str(), 4096, 0, 1, 2, 3, 4,
                        /*queue_depth=*/3, /*want_arenas=*/0, n_workers);
  if (!r) {
    std::fprintf(stderr, "open failed\n");
    std::exit(1);
  }
  std::vector<double> lat(4096), lon(4096);
  std::vector<int64_t> ts(4096);
  std::vector<int32_t> routed(4096);
  std::vector<uint8_t> bg(4096);
  std::vector<char> names(1 << 20);
  int64_t total = 0;
  int batches = 0;
  while (true) {
    int64_t ub, sb, nb;
    int64_t rows = hm_csv_peek(r, &ub, &sb, &nb);
    if (rows <= 0) break;
    if (nb > static_cast<int64_t>(names.size())) names.resize(nb);
    hm_csv_take(r, lat.data(), lon.data(), ts.data(), nullptr, nullptr,
                routed.data(), bg.data(), names.data());
    total += rows;
    if (early_close && ++batches == 3) break;  // close mid-stream
  }
  hm_csv_close(r);
  return total;
}

void pool_hammer() {
  void* pool = hm_pool_create(1 << 16, 3);
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; ++t) {
    ts.emplace_back([pool, t] {
      for (int i = 0; i < 2000; ++i) {
        int id = hm_pool_acquire(pool);
        auto* p = static_cast<int64_t*>(hm_pool_buffer(pool, id));
        p[0] = t * 1000000 + i;  // touch the buffer
        if (p[0] < 0) std::abort();
        hm_pool_release(pool, id);
      }
    });
  }
  for (auto& th : ts) th.join();
  hm_pool_destroy(pool);
}

}  // namespace

int main() {
  std::string path = write_csv();
  int64_t a = drain(path, 1, false);
  int64_t b = drain(path, 4, false);
  if (a != kRows || b != kRows) {
    std::fprintf(stderr, "row mismatch: w1=%lld w4=%lld want %d\n",
                 static_cast<long long>(a), static_cast<long long>(b), kRows);
    return 1;
  }
  drain(path, 4, true);  // early close: destructor races
  pool_hammer();
  // Threaded blob formatter: 1-thread and 8-thread outputs must be
  // byte-identical (slice boundaries are the racy part).
  {
    constexpr int64_t n = 50000;
    std::vector<int64_t> rows(n), cols(n);
    std::vector<double> vals(n);
    std::vector<uint8_t> starts(n);
    for (int64_t i = 0; i < n; ++i) {
      rows[i] = (i * 7919) % 32768;
      cols[i] = (i * 104729) % 32768;
      vals[i] = static_cast<double>((i % 1000) + 1);
      starts[i] = (i == 0 || i % 5 == 0) ? 1 : 0;
    }
    char* one = nullptr;
    char* eight = nullptr;
    int64_t l1 = hm_format_blob_bodies(rows.data(), cols.data(), vals.data(),
                                       starts.data(), n, 15, 1, &one);
    int64_t l8 = hm_format_blob_bodies(rows.data(), cols.data(), vals.data(),
                                       starts.data(), n, 15, 8, &eight);
    if (l1 != l8 || l1 < 0 || std::memcmp(one, eight, l1) != 0) {
      std::fprintf(stderr, "blobfmt thread mismatch: %lld vs %lld\n",
                   static_cast<long long>(l1), static_cast<long long>(l8));
      return 1;
    }
    hm_blobfmt_free(one);
    hm_blobfmt_free(eight);
  }
  // Threaded key decoder: 1-thread and 8-thread outputs must match
  // exactly (threads write disjoint ranges of shared output buffers;
  // the minimum-per-thread floor is the subtle part, so use an n
  // large enough to actually fan out).
  {
    // >= 8 * the 2^16 per-thread floor, so n_threads=8 really fans out
    // to 8 threads rather than being silently capped.
    constexpr int64_t n = 1 << 19;
    std::vector<int64_t> keys(n);
    for (int64_t i = 0; i < n; ++i)
      keys[i] = ((i % 37) << 42) | ((i * 2654435761LL) & ((1LL << 42) - 1));
    std::vector<int32_t> s1(n), s8(n), r1(n), r8(n), c1(n), c8(n);
    std::vector<int64_t> k1(n), k8(n);
    if (hm_decode_keys(keys.data(), n, 42, s1.data(), k1.data(), r1.data(),
                       c1.data(), 1) != 0 ||
        hm_decode_keys(keys.data(), n, 42, s8.data(), k8.data(), r8.data(),
                       c8.data(), 8) != 0 ||
        std::memcmp(s1.data(), s8.data(), n * 4) != 0 ||
        std::memcmp(k1.data(), k8.data(), n * 8) != 0 ||
        std::memcmp(r1.data(), r8.data(), n * 4) != 0 ||
        std::memcmp(c1.data(), c8.data(), n * 4) != 0) {
      std::fprintf(stderr, "decode_keys thread mismatch\n");
      return 1;
    }
    // Morton-only form: null slot/code outputs, threaded.
    std::vector<int32_t> rm(n), cm(n);
    if (hm_decode_keys(keys.data(), n, 0, nullptr, nullptr, rm.data(),
                       cm.data(), 8) != 0) {
      std::fprintf(stderr, "decode_keys null-column form failed\n");
      return 1;
    }
  }
  // Threaded blob-id formatter: 1-thread and 8-thread outputs must be
  // byte-identical across slice boundaries.
  {
    constexpr int64_t n = 1 << 19;
    // Packed like the Python _name_table: concatenated, NO separators.
    const char unames[] = "allrouteuser-7";
    const int64_t uoffs[] = {0, 3, 8, 14};
    const char tnames[] = "alltime2017_02_03";
    const int64_t toffs[] = {0, 7, 17};
    std::vector<int32_t> ui(n), ti(n), cr(n), cc(n);
    for (int64_t i = 0; i < n; ++i) {
      ui[i] = static_cast<int32_t>(i % 3);
      ti[i] = static_cast<int32_t>(i % 2);
      cr[i] = static_cast<int32_t>((i * 31) % 65536);
      cc[i] = static_cast<int32_t>((i * 17) % 65536);
    }
    char* one = nullptr;
    char* eight = nullptr;
    int64_t l1 = hm_format_blob_ids(ui.data(), ti.data(), cr.data(),
                                    cc.data(), n, 11, unames, uoffs, 3,
                                    tnames, toffs, 2, 1, &one);
    int64_t l8 = hm_format_blob_ids(ui.data(), ti.data(), cr.data(),
                                    cc.data(), n, 11, unames, uoffs, 3,
                                    tnames, toffs, 2, 8, &eight);
    if (l1 != l8 || l1 < 0 || std::memcmp(one, eight, l1) != 0) {
      std::fprintf(stderr, "blob-id thread mismatch: %lld vs %lld\n",
                   static_cast<long long>(l1), static_cast<long long>(l8));
      return 1;
    }
    hm_blobfmt_free(one);
    hm_blobfmt_free(eight);
  }
  std::remove(path.c_str());
  std::printf(
      "tsan selftest ok: %lld rows x2, early-close, pool hammer, blobfmt, "
      "blob-ids, decode_keys\n",
      static_cast<long long>(a));
  return 0;
}
