// Native point-record codec: parallel CSV decoder with batch prefetch.
//
// TPU-native replacement for the ingest decoding the reference delegated
// to the JVM spark-cassandra-connector (reference Dockerfile:5,
// submit-heatmap:15) and to per-row Python in dataframe_loader (reference
// heatmap.py:25-36). Design:
//
//   * N worker threads shard the file by byte range — the same
//     split-at-record-boundaries strategy Spark's connector uses over
//     Cassandra token ranges. Each worker parses complete lines (a line
//     belongs to the worker whose range contains the byte BEFORE its
//     first character; every worker skips through its first newline,
//     which also skips the header) into columnar batches pushed onto one
//     bounded queue. The consumer (Python via ctypes) overlaps device
//     compute with parsing.
//   * Numeric columns parse with std::from_chars (correctly rounded,
//     locale-independent — bit-identical to CPython float()).
//   * User-id routing (reference heatmap.py:64-70) and the background
//     filter flag (heatmap.py:28-29) are computed in-native; routed
//     group names are interned in a shared table (shared_mutex: lock-free
//     reads on the hot hit path would need a concurrent map; a reader-
//     writer lock is within noise here since misses are rare after
//     warmup) and streamed to the consumer incrementally in id order.
//   * The ABI is plain C (ctypes-friendly; pybind11 is not available in
//     the build image): peek (block for sizes) then take (copy into
//     caller-owned numpy buffers).
//
// Quoting: RFC4180 quoted fields with "" escapes are handled within a
// line; embedded newlines inside quoted fields are NOT (GPS point feeds
// never contain them; the Python csv fallback covers pathological files).
// Batch ORDER is nondeterministic with n_workers > 1 — the aggregation
// pipeline is order-invariant (sum-reduce); pass n_workers=1 where byte
// order matters (the compat string path does).

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <charconv>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <string_view>
#include <sys/stat.h>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kTsMissing = INT64_MIN;
constexpr size_t kChunkBytes = 4u << 20;

struct Batch {
  std::vector<double> lat, lon;
  std::vector<int64_t> ts;
  std::string uid_arena;  // NUL-terminated fields, one per row
  std::string src_arena;  // NUL-terminated fields, one per row
  // Fast path: per-row routed group id into the shared intern table
  // (-1 = excluded x-user) and background flags.
  std::vector<int32_t> routed;
  std::vector<uint8_t> background;
  // Intern-table size when this batch was finalized: every routed id in
  // the batch is < names_upto, so delivering names [delivered,
  // names_upto) with the batch keeps the consumer's table sufficient.
  int64_t names_upto = 0;
  int64_t rows = 0;
};

struct Field {
  const char* p;          // resolved after split_fields returns
  size_t len;
  ptrdiff_t scratch_off;  // >= 0: field lives in scratch at this offset
};

// Split one line into fields. Quoted fields are copied (with "" escapes
// unfolded) into `scratch`; plain fields alias the line buffer. Pointers
// into scratch are resolved only once the whole line is parsed, since
// scratch may reallocate mid-line.
int split_fields(const char* line, size_t len, std::vector<Field>& out,
                 std::string& scratch) {
  out.clear();
  scratch.clear();
  size_t i = 0;
  while (true) {
    if (i < len && line[i] == '"') {
      size_t start = scratch.size();
      ++i;
      while (i < len) {
        if (line[i] == '"') {
          if (i + 1 < len && line[i + 1] == '"') {
            scratch.push_back('"');
            i += 2;
            continue;
          }
          ++i;
          break;
        }
        scratch.push_back(line[i++]);
      }
      out.push_back({nullptr, scratch.size() - start,
                     static_cast<ptrdiff_t>(start)});
      while (i < len && line[i] != ',') ++i;
    } else {
      size_t start = i;
      const char* comma =
          static_cast<const char*>(std::memchr(line + i, ',', len - i));
      i = comma ? static_cast<size_t>(comma - line) : len;
      size_t flen = i - start;
      // Trim a trailing \r on the last field.
      if (i == len && flen && line[start + flen - 1] == '\r') --flen;
      out.push_back({line + start, flen, -1});
    }
    if (i >= len) break;
    ++i;  // skip ','
    if (i == len) {  // trailing comma -> empty final field
      out.push_back({line + i, 0, -1});
      break;
    }
  }
  for (auto& f : out) {
    if (f.scratch_off >= 0) f.p = scratch.data() + f.scratch_off;
  }
  return static_cast<int>(out.size());
}

// Correctly-rounded, locale-independent float parse (std::from_chars) —
// bit-identical to CPython's float() for valid decimals. from_chars
// rejects a leading '+', which strtod/float() accept; skip it.
double parse_double(const Field& f) {
  const char* p = f.p;
  size_t len = f.len;
  if (len && (*p == '+')) {
    ++p;
    --len;
  }
  if (len == 0) return std::nan("");
  double v;
  auto r = std::from_chars(p, p + len, v);
  if (r.ec != std::errc() || r.ptr != p + len) {
    // Fall back for forms from_chars rejects ("inf", "nan", hex
    // floats). Full consumption required: a trailing-junk prefix parse
    // ("123abc") must not masquerade as a valid number.
    char buf[64];
    size_t n = f.len < sizeof(buf) - 1 ? f.len : sizeof(buf) - 1;
    std::memcpy(buf, f.p, n);
    buf[n] = '\0';
    char* end = nullptr;
    v = std::strtod(buf, &end);
    if (end != buf + n) return std::nan("");
  }
  return v;
}

int64_t parse_ts(const Field& f) {
  const char* p = f.p;
  size_t len = f.len;
  if (len && (*p == '+')) {
    ++p;
    --len;
  }
  if (len == 0) return kTsMissing;
  int64_t v;
  auto r = std::from_chars(p, p + len, v);
  if (r.ec == std::errc() && r.ptr == p + len) return v;
  // Non-integer timestamp ('1.5e12', ISO fragments…): epoch-ms floats
  // round-trip through double like the Python path's float(ts) does.
  double d = parse_double(f);
  if (std::isfinite(d)) return static_cast<int64_t>(std::llround(d));
  return kTsMissing;
}

struct CsvReader {
  std::string path;
  int64_t batch_rows;
  int lat_col, lon_col, uid_col, src_col, ts_col;
  size_t queue_depth;
  bool want_arenas = true;  // false: fast mode, skip per-row string copies

  // Shared routed-name intern table. Keys are views into `owned_names`
  // (deque: stable addresses). Guarded by intern_mu; name_bytes_prefix
  // lets peek size an id range's NUL-separated byte span in O(1).
  std::shared_mutex intern_mu;
  std::unordered_map<std::string_view, int32_t> intern;
  std::deque<std::string> owned_names;
  std::vector<int64_t> name_bytes_prefix{0};

  std::vector<std::thread> workers;
  int active = 0;  // workers still running (guarded by mu)
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::deque<Batch> queue;
  bool done = false;  // every worker finished (EOF or error)
  std::atomic<bool> stop{false};
  std::string error;
  int64_t delivered_names = 0;  // consumer-thread state (peek/take only)

  ~CsvReader() {
    stop.store(true);
    cv_push.notify_all();
    for (auto& w : workers)
      if (w.joinable()) w.join();
  }

  void push(Batch&& b) {
    std::unique_lock<std::mutex> lk(mu);
    cv_push.wait(lk, [&] { return queue.size() < queue_depth || stop.load(); });
    if (stop.load()) return;
    queue.push_back(std::move(b));
    cv_pop.notify_one();
  }

  void worker_finish(const char* err) {
    std::lock_guard<std::mutex> lk(mu);
    if (err && error.empty()) {
      error = err;
      stop.store(true);
      cv_push.notify_all();
    }
    if (--active == 0 || err) done = true;
    cv_pop.notify_all();
  }

  int32_t route(const Field& u) {
    // Reference heatmap.py:64-70: 'x'-prefix excluded, 'rt-' pooled
    // under "route", everyone else their own group.
    if (u.len >= 1 && u.p[0] == 'x') return -1;
    std::string_view name = (u.len >= 3 && std::memcmp(u.p, "rt-", 3) == 0)
                                ? std::string_view("route")
                                : std::string_view(u.p, u.len);
    {
      std::shared_lock<std::shared_mutex> lk(intern_mu);
      auto it = intern.find(name);
      if (it != intern.end()) return it->second;
    }
    std::unique_lock<std::shared_mutex> lk(intern_mu);
    auto it = intern.find(name);  // lost a race?
    if (it != intern.end()) return it->second;
    int32_t id = static_cast<int32_t>(intern.size());
    owned_names.emplace_back(name);
    intern.emplace(std::string_view(owned_names.back()), id);
    name_bytes_prefix.push_back(
        name_bytes_prefix.back() +
        static_cast<int64_t>(owned_names.back().size()) + 1);
    return id;
  }

  int64_t names_size() {
    std::shared_lock<std::shared_mutex> lk(intern_mu);
    return static_cast<int64_t>(owned_names.size());
  }

  // Parse lines whose first byte follows a newline in [begin, end); the
  // worker with begin == 0 drops the header via the same skip-through-
  // first-newline rule (the Python side re-reads it for column mapping).
  void worker_run(int64_t begin, int64_t end_abs) {
    FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) {
      worker_finish("worker open failed");
      return;
    }
    // 64-bit seek regardless of the width of long: fseek(long) would
    // truncate offsets past 2 GiB on LLP64 platforms and misplace the
    // worker's shard.
#if defined(_WIN32)
    if (begin > 0 && _fseeki64(f, begin, SEEK_SET) != 0) {
#else
    if (begin > 0 && fseeko(f, static_cast<off_t>(begin), SEEK_SET) != 0) {
#endif
      std::fclose(f);
      worker_finish("worker seek failed");
      return;
    }
    std::vector<char> chunk(kChunkBytes);
    std::string carry;
    std::vector<Field> fields;
    std::string scratch;
    Batch cur;
    cur.lat.reserve(batch_rows);
    cur.lon.reserve(batch_rows);
    cur.ts.reserve(batch_rows);

    auto emit_line = [&](const char* line, size_t len) {
      if (len == 0) return;
      int n = split_fields(line, len, fields, scratch);
      auto get = [&](int c) -> Field {
        if (c < 0 || c >= n) return {line, 0, -1};
        return fields[c];
      };
      cur.lat.push_back(parse_double(get(lat_col)));
      cur.lon.push_back(parse_double(get(lon_col)));
      cur.ts.push_back(ts_col >= 0 ? parse_ts(get(ts_col)) : kTsMissing);
      Field u = get(uid_col);
      Field s = get(src_col);
      if (want_arenas) {
        // Compat (string) mode: the consumer re-derives routing from
        // the arenas, so skip the per-row intern-lock traffic.
        cur.uid_arena.append(u.p, u.len);
        cur.uid_arena.push_back('\0');
        cur.src_arena.append(s.p, s.len);
        cur.src_arena.push_back('\0');
      } else {
        cur.routed.push_back(route(u));
        cur.background.push_back(
            s.len == 10 && std::memcmp(s.p, "background", 10) == 0 ? 1 : 0);
      }
      if (++cur.rows >= batch_rows) {
        cur.names_upto = names_size();
        push(std::move(cur));
        cur = Batch();
        cur.lat.reserve(batch_rows);
        cur.lon.reserve(batch_rows);
        cur.ts.reserve(batch_rows);
      }
    };

    bool skipping = true;       // until the first newline is consumed
    bool done_range = false;
    int64_t chunk_abs = begin;  // file offset of chunk[0]
    int64_t line_start_abs = begin;
    while (!stop.load() && !done_range) {
      size_t got = std::fread(chunk.data(), 1, chunk.size(), f);
      if (got == 0) {
        if (std::ferror(f)) {
          std::fclose(f);
          worker_finish("read error");
          return;
        }
        break;  // EOF
      }
      size_t seg_begin = 0;
      for (size_t i = 0; i < got; ++i) {
        if (chunk[i] != '\n') continue;
        if (skipping) {
          skipping = false;
        } else {
          if (!carry.empty()) {
            carry.append(chunk.data() + seg_begin, i - seg_begin);
            emit_line(carry.data(), carry.size());
            carry.clear();
          } else {
            emit_line(chunk.data() + seg_begin, i - seg_begin);
          }
        }
        seg_begin = i + 1;
        line_start_abs = chunk_abs + static_cast<int64_t>(i) + 1;
        if (line_start_abs > end_abs) {
          done_range = true;
          break;
        }
      }
      if (!done_range && !skipping)
        carry.append(chunk.data() + seg_begin, got - seg_begin);
      chunk_abs += static_cast<int64_t>(got);
    }
    // Final line without a trailing newline (last worker only).
    if (!stop.load() && !done_range && !skipping && !carry.empty() &&
        line_start_abs <= end_abs)
      emit_line(carry.data(), carry.size());
    std::fclose(f);
    if (!stop.load() && cur.rows > 0) {
      cur.names_upto = names_size();
      push(std::move(cur));
    }
    worker_finish(nullptr);
  }
};

}  // namespace

extern "C" {

// Column indices are 0-based positions in the header row (parsed by the
// caller); pass -1 for a column absent from the file.
void* hm_csv_open(const char* path, int64_t batch_rows, int lat_col,
                  int lon_col, int uid_col, int src_col, int ts_col,
                  int queue_depth, int want_arenas, int n_workers) {
  if (batch_rows <= 0 || queue_depth <= 0 || n_workers <= 0) return nullptr;
  struct stat st;
  if (::stat(path, &st) != 0) return nullptr;
  int64_t size = static_cast<int64_t>(st.st_size);
  auto* r = new CsvReader();
  r->path = path;
  r->batch_rows = batch_rows;
  r->lat_col = lat_col;
  r->lon_col = lon_col;
  r->uid_col = uid_col;
  r->src_col = src_col;
  r->ts_col = ts_col;
  r->queue_depth = static_cast<size_t>(queue_depth);
  r->want_arenas = want_arenas != 0;
  // No point sharding tiny files across threads.
  int64_t min_span = 1 << 20;
  int w = static_cast<int>(
      std::min<int64_t>(n_workers, std::max<int64_t>(1, size / min_span)));
  r->active = w;
  for (int i = 0; i < w; ++i) {
    int64_t begin = size * i / w;
    int64_t end_abs = size * (i + 1) / w;
    r->workers.emplace_back([r, begin, end_abs] {
      r->worker_run(begin, end_abs);
    });
  }
  return r;
}

// Block until a batch is ready (or EOF/error). Returns rows in the next
// batch (0 = EOF, -1 = error; see hm_csv_error) and writes the byte
// sizes of its string payloads so the caller can allocate exact buffers.
// new_names_bytes covers routed-group names [delivered, names_upto) —
// the names the consumer hasn't seen yet, in id order.
int64_t hm_csv_peek(void* handle, int64_t* uid_bytes, int64_t* src_bytes,
                    int64_t* new_names_bytes) {
  auto* r = static_cast<CsvReader*>(handle);
  std::unique_lock<std::mutex> lk(r->mu);
  r->cv_pop.wait(lk, [&] { return !r->queue.empty() || r->done; });
  if (!r->error.empty()) return -1;
  if (r->queue.empty()) return 0;
  const Batch& b = r->queue.front();
  *uid_bytes = static_cast<int64_t>(b.uid_arena.size());
  *src_bytes = static_cast<int64_t>(b.src_arena.size());
  int64_t upto = b.names_upto;
  lk.unlock();
  {
    std::shared_lock<std::shared_mutex> ilk(r->intern_mu);
    int64_t from = r->delivered_names;
    *new_names_bytes = upto > from ? r->name_bytes_prefix[upto] -
                                         r->name_bytes_prefix[from]
                                   : 0;
  }
  return b.rows;
}

// Copy the peeked batch into caller-owned buffers (sized per hm_csv_peek)
// and pop it. Any output pointer may be NULL to skip that column — the
// fast path skips the per-row string arenas; the compat path skips the
// routed/background columns. Returns 0 on success, -1 if no batch is
// pending.
int hm_csv_take(void* handle, double* lat, double* lon, int64_t* ts,
                char* uid_arena, char* src_arena, int32_t* routed,
                uint8_t* background, char* new_names_arena) {
  auto* r = static_cast<CsvReader*>(handle);
  Batch b;
  {
    std::lock_guard<std::mutex> lk(r->mu);
    if (r->queue.empty()) return -1;
    b = std::move(r->queue.front());
    r->queue.pop_front();
    r->cv_push.notify_one();
  }
  if (lat) std::memcpy(lat, b.lat.data(), sizeof(double) * b.rows);
  if (lon) std::memcpy(lon, b.lon.data(), sizeof(double) * b.rows);
  if (ts) std::memcpy(ts, b.ts.data(), sizeof(int64_t) * b.rows);
  if (uid_arena)
    std::memcpy(uid_arena, b.uid_arena.data(), b.uid_arena.size());
  if (src_arena)
    std::memcpy(src_arena, b.src_arena.data(), b.src_arena.size());
  if (routed) std::memcpy(routed, b.routed.data(), sizeof(int32_t) * b.rows);
  if (background) std::memcpy(background, b.background.data(), b.rows);
  if (new_names_arena && b.names_upto > r->delivered_names) {
    std::shared_lock<std::shared_mutex> ilk(r->intern_mu);
    char* out = new_names_arena;
    for (int64_t i = r->delivered_names; i < b.names_upto; ++i) {
      const std::string& n = r->owned_names[static_cast<size_t>(i)];
      std::memcpy(out, n.data(), n.size());
      out += n.size();
      *out++ = '\0';
    }
  }
  if (b.names_upto > r->delivered_names) r->delivered_names = b.names_upto;
  return 0;
}

const char* hm_csv_error(void* handle) {
  auto* r = static_cast<CsvReader*>(handle);
  std::lock_guard<std::mutex> lk(r->mu);
  return r->error.c_str();
}

void hm_csv_close(void* handle) { delete static_cast<CsvReader*>(handle); }

int64_t hm_ts_missing(void) { return kTsMissing; }

}  // extern "C"
